"""Lowering from variables/constraints to dense padded problem tensors.

This is the TPU-native equivalent of the reference's ``LitMapping``
(/root/reference/pkg/sat/lit_mapping.go:40-77): a two-pass construction that
(1) assigns an index to every variable, rejecting duplicates
(lit_mapping.go:49-57), and (2) lowers every constraint into solver inputs
(lit_mapping.go:59-74).  Where the reference builds a gini logic circuit and
Tseitin-translates it to CNF (lit_mapping.go:132-134), this encoder emits:

  * a dense padded clause matrix ``clauses: int32[C, K]`` in signed-DIMACS
    convention (literal ``v+1`` means "variable v true", ``-(v+1)`` false,
    ``0`` is padding), and
  * native **cardinality rows** for ``AtMost`` constraints, which the tensor
    engine propagates directly (count true members; ``> n`` is a conflict,
    ``== n`` forces the rest false).  This replaces gini's sorting-network
    ``CardSort`` (reference constraints.go:180-186) — a pointer-heavy circuit
    that lowers poorly to dense tensors — with an arc-consistency-equivalent
    formulation that is a single masked reduction on the MXU/VPU.

Activation variables: every applied constraint ``j`` owns an auxiliary
boolean ``N + j`` that guards its clauses (``act → clause``).  Assuming all
activation variables true enforces every constraint — the analog of
``AssumeConstraints`` (lit_mapping.go:136-140) — while unsat-core extraction
re-solves with subsets of activations enabled, the analog of gini's
failed-assumption ``Why`` (lit_mapping.go:198-207).

Index conventions used throughout the framework:
  * clause literals: signed 1-based, 0 = padding;
  * every other index tensor: 0-based, -1 = padding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

from .constraints import (
    AppliedConstraint,
    AtMost,
    Conflict,
    Dependency,
    Identifier,
    Mandatory,
    Prohibited,
    Variable,
    mandatory,
    prohibited,
)
from .errors import DuplicateIdentifier


@dataclass
class Problem:
    """A fully lowered constraint-resolution problem.

    Host-side metadata (variables, applied constraints, id maps) lives
    alongside the dense tensors so solutions and unsat cores can be decoded
    back to the caller's vocabulary, mirroring LitMapping's bidirectional
    translation role (lit_mapping.go:24-34).
    """

    # --- host metadata ---
    variables: List[Variable]                 # input order (inorder, lit_mapping.go:28)
    applied: List[AppliedConstraint]          # global constraint order
    id_to_index: Dict[Identifier, int]
    errors: List[str]                         # missing-reference errors (lit_mapping.go:81-88)

    # --- dense tensors (numpy; batching/jit conversion happens downstream) ---
    clauses: np.ndarray        # int32[C, K]   signed 1-based lits, 0 pad
    clause_con: np.ndarray     # int32[C]      applied-constraint index per clause
    card_ids: np.ndarray       # int32[NA, M]  0-based member var indices, -1 pad
    card_n: np.ndarray         # int32[NA]     bound per row
    card_act: np.ndarray       # int32[NA]     0-based activation var index
    card_con: np.ndarray       # int32[NA]     applied-constraint index per row
    anchors: np.ndarray        # int32[A]      0-based anchor var indices, input order
    choice_cand: np.ndarray    # int32[NC, Kc] candidate var indices per choice, -1 pad
    var_choices: np.ndarray    # int32[N, W]   choice rows spawned by guessing var, -1 pad

    @property
    def n_vars(self) -> int:
        """Number of problem variables (entities)."""
        return len(self.variables)

    @property
    def n_cons(self) -> int:
        """Number of applied constraints (= number of activation vars)."""
        return len(self.applied)

    @property
    def n_total(self) -> int:
        """Total boolean variables: problem vars + activation vars."""
        return self.n_vars + self.n_cons

    def act_index(self, j: int) -> int:
        """Activation variable index of applied constraint ``j``."""
        return self.n_vars + j


def _pad2d(rows: Sequence[Sequence[int]], pad: int, min_width: int = 1) -> np.ndarray:
    lens = np.fromiter((len(r) for r in rows), np.int64, count=len(rows))
    width = max(int(lens.max(initial=0)), min_width)
    out = np.full((len(rows), width), pad, dtype=np.int32)
    # One flattened fill via a ragged mask instead of a per-row python
    # loop: encode() is the framework's hottest pure-host path and this
    # function was ~1/3 of it.
    flat = np.fromiter(
        (x for r in rows for x in r), np.int32, count=int(lens.sum()))
    out[np.arange(width) < lens[:, None]] = flat
    return out


def encode(variables: Sequence[Variable]) -> Problem:
    """Lower ``variables`` to a :class:`Problem`.

    Raises :class:`DuplicateIdentifier` on repeated identifiers
    (lit_mapping.go:52-54); accumulates references to unprovided identifiers
    in ``Problem.errors`` instead of failing, matching the deferred
    internal-error contract of the reference (lit_mapping.go:81-88 with the
    deferred check at solve.go:54-61).
    """
    variables = list(variables)
    id_to_index: Dict[Identifier, int] = {}
    for i, v in enumerate(variables):
        if v.identifier in id_to_index:
            raise DuplicateIdentifier(v.identifier)
        id_to_index[v.identifier] = i
    n = len(variables)

    errors: List[str] = []

    def lookup(ident: Identifier) -> int:
        idx = id_to_index.get(ident)
        if idx is None:
            errors.append(f'variable "{ident}" referenced but not provided')
            return -1
        return idx

    applied: List[AppliedConstraint] = []
    clause_rows: List[List[int]] = []
    clause_con: List[int] = []
    card_rows: List[List[int]] = []
    card_n: List[int] = []
    card_act: List[int] = []
    card_con: List[int] = []
    anchors: List[int] = []
    anchor_set = set()
    # Choice table: rows 0..A-1 are anchor singletons (seeded into the search
    # deque in input order, reference search.go:159-161); subsequent rows are
    # Dependency candidate lists in global constraint order, spawned when
    # their subject variable is guessed (search.go:60-69).
    dep_choice_rows: List[List[int]] = []
    var_dep_choices: List[List[int]] = [[] for _ in range(n)]

    for i, v in enumerate(variables):
        for con in v.constraints:
            j = len(applied)
            applied.append(AppliedConstraint(v, con))
            act = n + j  # activation var; clause form is (¬act ∨ formula)
            subj = i
            if isinstance(con, Mandatory):
                clause_rows.append([-(act + 1), subj + 1])
                clause_con.append(j)
                if i not in anchor_set:
                    anchor_set.add(i)
                    anchors.append(i)
            elif isinstance(con, Prohibited):
                clause_rows.append([-(act + 1), -(subj + 1)])
                clause_con.append(j)
            elif isinstance(con, Dependency):
                # (¬act ∨ ¬subject ∨ id₁ ∨ id₂ …) — reference builds the same
                # disjunction as an Or-gate fold (constraints.go:117-123).  An
                # empty Dependency degenerates to (¬act ∨ ¬subject): the
                # subject cannot be installed (constraints.go:107-108).
                # Duplicate target literals are dropped (x ∨ x ≡ x) so the
                # per-occurrence and per-variable (bitplane) propagation
                # counts agree on every clause.
                row = [-(act + 1), -(subj + 1)]
                seen_lits = set(row)
                for ident in con.ids:
                    t = lookup(ident)
                    if t >= 0 and (t + 1) not in seen_lits:
                        seen_lits.add(t + 1)
                        row.append(t + 1)
                clause_rows.append(row)
                clause_con.append(j)
                if con.ids:
                    cid = len(dep_choice_rows)
                    # Candidates are exactly the resolved tail of the clause.
                    dep_choice_rows.append([lit - 1 for lit in row[2:]])
                    var_dep_choices[i].append(cid)
            elif isinstance(con, Conflict):
                # Self-conflict (id == subject) degenerates to ¬subject;
                # dedup keeps the per-occurrence and bitplane counts equal.
                t = lookup(con.id)
                row = [-(act + 1), -(subj + 1)]
                if t >= 0 and -(t + 1) not in row:
                    row.append(-(t + 1))
                clause_rows.append(row)
                clause_con.append(j)
            elif isinstance(con, AtMost):
                # Dedup members: bitplane cardinality rows count each
                # variable once, so the dense row must as well.
                members = []
                for ident in con.ids:
                    m = lookup(ident)
                    if m >= 0 and m not in members:
                        members.append(m)
                card_rows.append(members)
                card_n.append(con.n)
                card_act.append(act)
                card_con.append(j)
            else:  # pragma: no cover - defensive
                errors.append(f"unknown constraint type {type(con).__name__!r}")

    a = len(anchors)
    # Final choice table: anchor singletons first, then dependency rows
    # (dependency choice ids shift by ``a``).
    choice_rows: List[List[int]] = [[x] for x in anchors] + dep_choice_rows
    var_choices = [[a + cid for cid in cids] for cids in var_dep_choices]

    return Problem(
        variables=variables,
        applied=applied,
        id_to_index=id_to_index,
        errors=errors,
        clauses=_pad2d(clause_rows, pad=0) if clause_rows else np.zeros((0, 1), np.int32),
        clause_con=np.asarray(clause_con, dtype=np.int32),
        card_ids=_pad2d(card_rows, pad=-1) if card_rows else np.zeros((0, 1), np.int32),
        card_n=np.asarray(card_n, dtype=np.int32),
        card_act=np.asarray(card_act, dtype=np.int32),
        card_con=np.asarray(card_con, dtype=np.int32),
        anchors=np.asarray(anchors, dtype=np.int32),
        choice_cand=_pad2d(choice_rows, pad=-1) if choice_rows else np.zeros((0, 1), np.int32),
        var_choices=_pad2d(var_choices, pad=-1) if var_choices else np.zeros((0, 1), np.int32),
    )


def encode_assumed(problem: Problem,
                   assumptions: Sequence[Tuple[Identifier, bool]]) -> Problem:
    """O(delta) relowering of an already-encoded ``problem`` under an
    assumption stack: each ``(identifier, installed)`` pair becomes a
    ``Mandatory`` (installed) or ``Prohibited`` constraint on its
    subject variable — exactly ``encode(assumed_variables(...))``, built
    by splicing the assumption unit clauses into the retained tensors
    instead of re-walking the whole catalog (ISSUE 20: a session's
    per-step cost must scale with the CHANGE, not the catalog).

    The dense tensors are byte-identical to the full relowering's —
    pinned by the differential test — because an assumption constraint
    lowers to one unit clause inserted at the end of its subject
    variable's applied block: every later applied index (and therefore
    every later activation literal) shifts by the insertion count before
    it, Mandatory subjects join ``anchors`` in variable order, and the
    anchor-singleton head of the choice table regrows around the
    untouched dependency rows.  Unknown identifiers are dropped, exactly
    as :func:`deppy_tpu.sat.solver.assumed_variables` drops them.

    Tensors the delta cannot touch (``card_ids``/``card_n``, and the
    choice tables when no new anchor appears) are SHARED with the base
    problem, not copied — every consumer treats problem tensors as
    read-only."""
    if not assumptions:
        return problem
    n = problem.n_vars
    by_var: Dict[int, List[bool]] = {}
    for ident, installed in assumptions:
        idx = problem.id_to_index.get(ident)
        if idx is not None:
            by_var.setdefault(idx, []).append(bool(installed))
    if not by_var:
        return problem
    # Cumulative applied-constraint count per variable: cum[i + 1] is
    # the applied index where variable i's block ends — the insertion
    # point for its assumption constraints.  Memoized: the facade calls
    # this per step against ONE retained base problem.
    cum = problem.__dict__.get("_assume_cum")
    if cum is None:
        cum = np.concatenate([
            np.zeros(1, np.int64),
            np.cumsum(np.fromiter((len(v.constraints)
                                   for v in problem.variables),
                                  np.int64, count=n))])
        problem.__dict__["_assume_cum"] = cum
    ins_vars: List[int] = []
    ins_installed: List[bool] = []
    ins_pos: List[int] = []
    for i in sorted(by_var):
        for flag in by_var[i]:
            ins_vars.append(i)
            ins_installed.append(flag)
            ins_pos.append(int(cum[i + 1]))
    k = len(ins_pos)
    pos = np.asarray(ins_pos, dtype=np.int64)

    def remap(j) -> np.ndarray:
        """Old applied index -> new: shifted past every insertion at or
        before it."""
        j = np.asarray(j, dtype=np.int64)
        return j + np.searchsorted(pos, j, side="right")

    new_j = pos + np.arange(k, dtype=np.int64)   # inserted applied idx
    acts = n + new_j                             # their activation vars

    # Clause matrix: renumber activation literals, splice unit rows in
    # applied order (clause rows ARE in applied order — AtMost rows
    # live in the cardinality tensors).
    c = problem.clauses
    if c.size:
        cc = c.astype(np.int64)
        m = np.abs(cc) > n
        vals = np.abs(cc[m]) - 1 - n
        cc[m] = np.sign(cc[m]) * (n + remap(vals) + 1)
    else:
        cc = np.zeros((0, 2), np.int64)
    rows = np.zeros((k, cc.shape[1]), dtype=np.int64)
    rows[:, 0] = -(acts + 1)
    subj = np.asarray(ins_vars, dtype=np.int64) + 1
    rows[:, 1] = np.where(np.asarray(ins_installed, dtype=bool),
                          subj, -subj)
    r_ins = np.searchsorted(problem.clause_con, pos, side="left")
    clauses_new = np.insert(cc, r_ins, rows, axis=0).astype(np.int32)
    clause_con_new = np.insert(remap(problem.clause_con), r_ins,
                               new_j).astype(np.int32)

    if problem.card_act.size:
        card_act_new = (n + remap(problem.card_act.astype(np.int64) - n)
                        ).astype(np.int32)
        card_con_new = remap(problem.card_con).astype(np.int32)
    else:
        card_act_new = problem.card_act
        card_con_new = problem.card_con

    # Anchors: Mandatory assumptions promote their subjects.  encode()
    # appends anchors in variable order, so the merged list is the
    # sorted union.
    mand = {v for v, flag in zip(ins_vars, ins_installed) if flag}
    base_anchor = set(problem.anchors.tolist())
    anchors_new = problem.anchors
    choice_cand_new = problem.choice_cand
    var_choices_new = problem.var_choices
    if mand - base_anchor:
        anchors_new = np.asarray(sorted(base_anchor | mand),
                                 dtype=np.int32)
        a_old = problem.anchors.size
        a_new = anchors_new.size
        dep = (problem.choice_cand[a_old:] if problem.choice_cand.size
               else np.zeros((0, 1), np.int32))
        head = np.full((a_new, dep.shape[1]), -1, dtype=np.int32)
        head[:, 0] = anchors_new
        choice_cand_new = np.concatenate([head, dep], axis=0)
        vc = problem.var_choices
        var_choices_new = np.where(vc >= 0, vc + (a_new - a_old),
                                   vc).astype(np.int32)

    # Host metadata: extended Variable objects for assumed subjects,
    # fresh AppliedConstraint entries spliced into the applied order.
    variables_new = list(problem.variables)
    applied_new: List[AppliedConstraint] = []
    prev = 0
    for i in sorted(by_var):
        end = int(cum[i + 1])
        applied_new.extend(problem.applied[prev:end])
        prev = end
        v = problem.variables[i]
        cons = tuple(mandatory() if flag else prohibited()
                     for flag in by_var[i])
        nv = Variable(v.identifier, tuple(v.constraints) + cons)
        variables_new[i] = nv
        applied_new.extend(AppliedConstraint(nv, con) for con in cons)
    applied_new.extend(problem.applied[prev:])

    return Problem(
        variables=variables_new,
        applied=applied_new,
        id_to_index=problem.id_to_index,
        errors=list(problem.errors),
        clauses=clauses_new,
        clause_con=clause_con_new,
        card_ids=problem.card_ids,
        card_n=problem.card_n,
        card_act=card_act_new,
        card_con=card_con_new,
        anchors=anchors_new,
        choice_cand=choice_cand_new,
        var_choices=var_choices_new,
    )
