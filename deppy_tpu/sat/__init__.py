"""Boolean-constraint satisfiability layer.

The rebuild of the reference's ``pkg/sat`` (general-purpose solver for
boolean constraint satisfiability, /root/reference/pkg/sat/doc.go:1-3):
constraint vocabulary, dense tensor lowering, the host reference engine,
and the solver facade.  The TPU tensor engine lives in
:mod:`deppy_tpu.engine` and is selected via ``Solver(backend=...)``.
"""

from .constraints import (
    AppliedConstraint,
    AtMost,
    Conflict,
    Constraint,
    Dependency,
    Identifier,
    Mandatory,
    Prohibited,
    Variable,
    at_most,
    conflict,
    dependency,
    mandatory,
    prohibited,
    variable,
)
from .encode import Problem, encode
from .errors import (
    BackendCapabilityError,
    DuplicateIdentifier,
    Incomplete,
    InternalSolverError,
    NotSatisfiable,
)
from .host import HostEngine
from .solver import Solver, reprobe_engine, resolve_backend
from .tracer import DefaultTracer, LoggingTracer, SearchPosition, StatsTracer, Tracer

__all__ = [
    "AppliedConstraint",
    "AtMost",
    "BackendCapabilityError",
    "Conflict",
    "Constraint",
    "Dependency",
    "DefaultTracer",
    "DuplicateIdentifier",
    "HostEngine",
    "Identifier",
    "Incomplete",
    "InternalSolverError",
    "LoggingTracer",
    "Mandatory",
    "NotSatisfiable",
    "Problem",
    "Prohibited",
    "SearchPosition",
    "Solver",
    "StatsTracer",
    "reprobe_engine",
    "resolve_backend",
    "Tracer",
    "Variable",
    "at_most",
    "conflict",
    "dependency",
    "encode",
    "mandatory",
    "prohibited",
    "variable",
]
