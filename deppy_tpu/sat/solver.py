"""Solver facade for single problems.

The analog of the reference's ``sat.NewSolver``/``Solver.Solve``
(/root/reference/pkg/sat/solve.go:32-34,121-163).  The functional-options
pattern of the reference maps to plain keyword arguments; backends are
selected per solve:

  * ``"host"``  — the NumPy reference engine (semantic specification);
  * ``"tpu"``   — the batched tensor engine on the default JAX backend
    (one problem = batch of one);
  * ``"auto"``  — host for this single-problem facade (a batch of one is
    dispatch-latency-bound; the host engine wins every measured
    single-problem workload — BASELINE.md config 1); the batch facade's
    ``auto`` picks the tensor engine when a JAX backend is usable.

Usage::

    from deppy_tpu import sat
    s = sat.Solver([sat.variable("a", sat.mandatory())])
    installed = s.solve()          # -> [Variable("a", ...)]
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from .constraints import Variable
from .encode import Problem, encode
from .errors import InternalSolverError
from .host import HostEngine
from .tracer import Tracer


class Solver:
    """Preference-ordered, cardinality-minimized boolean-constraint solver.

    Construction validates input (raising ``DuplicateIdentifier`` like
    reference lit_mapping.go:49-57); ``solve`` returns the installed
    variables in input order, raises ``NotSatisfiable`` with a minimal core
    of applied constraints when no solution exists, or ``Incomplete`` when
    the step budget is exhausted.
    """

    def __init__(
        self,
        variables: Sequence[Variable],
        tracer: Optional[Tracer] = None,
        backend: str = "auto",
        max_steps: Optional[int] = None,
        trace_cap: Optional[int] = None,
    ):
        self.problem: Problem = encode(variables)
        self.tracer = tracer
        self.backend = backend
        self.max_steps = max_steps
        # Device-side trace buffer depth for the tensor backend (None =
        # driver default); the host engine traces unbuffered.
        self.trace_cap = trace_cap
        # Engine iterations consumed by the last solve (SURVEY.md §5).
        self.steps: int = 0

    def solve(self) -> List[Variable]:
        backend = resolve_backend(self.backend, batch=False)
        if backend == "host":
            engine = HostEngine(
                self.problem, tracer=self.tracer, max_steps=self.max_steps
            )
            try:
                installed, _ = engine.solve()
            finally:
                self.steps = engine.steps
            return installed
        from ..engine.driver import solve_one

        stats: dict = {}
        try:
            return solve_one(self.problem, max_steps=self.max_steps,
                             stats=stats, tracer=self.tracer,
                             trace_cap=self.trace_cap)
        finally:
            self.steps = stats.get("steps", 0)


def resolve_backend(backend: str, *, batch: bool = True) -> str:
    """Resolve a backend name to ``"host"`` or ``"tpu"``: the single place
    the ``auto`` policy lives (shared by :class:`Solver` and the resolution
    facade).  Raises on unknown names.

    ``batch=False`` marks a single-problem solve: ``auto`` picks the host
    engine there — a batch of one is dispatch-latency-bound and the serial
    host engine beats the device on every single-problem workload measured
    (BASELINE.md config 1: 67/s host vs 11/s device on the tunneled TPU).
    The tensor engine's win is batch parallelism; ``auto`` reserves it for
    batches.  Explicit ``"tpu"`` still forces the device path."""
    if backend == "auto":
        if not batch:
            return "host"
        return "tpu" if _engine_usable() else "host"
    if backend in ("host", "tpu"):
        return backend
    raise InternalSolverError([f"unknown backend {backend!r}"])


def _engine_usable() -> bool:
    """True when the tensor engine and a JAX backend are both importable.
    ``auto`` degrades to the host engine rather than failing, so the library
    stays usable on machines without a working accelerator runtime."""
    try:
        import jax

        jax.devices()
        from ..engine import driver  # noqa: F401

        return True
    except Exception:
        return False
