"""Solver facade for single problems.

The analog of the reference's ``sat.NewSolver``/``Solver.Solve``
(/root/reference/pkg/sat/solve.go:32-34,121-163).  The functional-options
pattern of the reference maps to plain keyword arguments; backends are
selected per solve:

  * ``"host"``  — the NumPy reference engine (semantic specification);
  * ``"tpu"``   — the batched tensor engine on the default JAX backend
    (one problem = batch of one);
  * ``"auto"``  — host for this single-problem facade (a batch of one is
    dispatch-latency-bound; the host engine wins every measured
    single-problem workload — BASELINE.md config 1); the batch facade's
    ``auto`` picks the tensor engine when a JAX backend is usable.

Usage::

    from deppy_tpu import sat
    s = sat.Solver([sat.variable("a", sat.mandatory())])
    installed = s.solve()          # -> [Variable("a", ...)]
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import Counter
from typing import List, Optional, Sequence

from .. import telemetry
from .constraints import Variable, mandatory, prohibited
from .encode import Problem, encode, encode_assumed
from .errors import Incomplete, InternalSolverError, NotSatisfiable
from .host import HostEngine
from .tracer import Tracer


def assumed_variables(variables: Sequence[Variable],
                      assumptions: Sequence[tuple]) -> List[Variable]:
    """Derive the variable list a solve under ``assumptions`` answers
    for: each ``(identifier, installed)`` assumption appends a
    ``Mandatory`` (installed) or ``Prohibited`` (excluded) constraint to
    its subject variable — the wire-level form of gini's assumption
    literals (ISSUE 20).  The derived list is an ordinary problem: a
    one-shot cold solve of it is byte-for-byte the oracle for the
    scoped solve, and its unsat cores render the assumption as a real
    applied constraint (``"x is mandatory"``) instead of a synthetic
    literal."""
    extra: dict = {}
    for ident, installed in assumptions:
        extra.setdefault(ident, []).append(
            mandatory() if installed else prohibited())
    if not extra:
        return list(variables)
    out = []
    for v in variables:
        added = extra.get(v.identifier)
        if added:
            out.append(Variable(v.identifier,
                                tuple(v.constraints) + tuple(added)))
        else:
            out.append(v)
    return out


class Solver:
    """Preference-ordered, cardinality-minimized boolean-constraint solver.

    Construction validates input (raising ``DuplicateIdentifier`` like
    reference lit_mapping.go:49-57); ``solve`` returns the installed
    variables in input order, raises ``NotSatisfiable`` with a minimal core
    of applied constraints when no solution exists, or ``Incomplete`` when
    the step budget is exhausted.
    """

    def __init__(
        self,
        variables: Sequence[Variable],
        tracer: Optional[Tracer] = None,
        backend: str = "auto",
        max_steps: Optional[int] = None,
        trace_cap: Optional[int] = None,
        scheduler=None,
        tenant: str = "default",
    ):
        self.problem: Problem = encode(variables)
        self.tracer = tracer
        self.backend = backend
        self.max_steps = max_steps
        # Device-side trace buffer depth for the tensor backend (None =
        # driver default); the host engine traces unbuffered.
        self.trace_cap = trace_cap
        # Engine iterations consumed by the last solve (SURVEY.md §5).
        self.steps: int = 0
        # Structured telemetry for the last solve (SURVEY.md §5 /
        # ISSUE 1): outcome, step/decision/propagation counters, and —
        # on the tensor backend — the driver's padding/escalation data.
        self.report: Optional[telemetry.SolveReport] = None
        # ISSUE 20: an attached request scheduler makes the scope model
        # engine-registry-aware — scoped solves route through
        # ``Scheduler.submit_session`` (deadlines/breaker/fair admission
        # and portfolio racing apply unchanged, and the shared result
        # cache is bypassed) instead of being pinned to the inline host
        # engine.  ``warm_index`` is the session's private clause-set
        # index, handed to the scheduler so scoped solves warm-start
        # from the session's own last model.
        self.scheduler = scheduler
        self.tenant = tenant
        self.warm_index = None

    # ------------------------------------------- incremental (ISSUE 10)
    #
    # The gini Assume/Test/Untest surface (reference solve.go:79,99,104)
    # the paper's L0 table names and the original build never
    # reproduced.  Scopes run on the host spec engine regardless of the
    # configured backend: a propagation-only Test is host-cheap, and the
    # tensor engine's batched entry points have no notion of a pinned
    # per-solver assumption stack.

    def _scope_engine(self) -> HostEngine:
        if getattr(self, "_inc_engine", None) is None:
            self._inc_engine = HostEngine(
                self.problem, tracer=self.tracer, max_steps=self.max_steps)
        return self._inc_engine

    def assume(self, *identifiers, installed: bool = True) -> None:
        """Assume each identifier's variable installed (or not, with
        ``installed=False``) for subsequent :meth:`test` scopes — the
        analog of gini ``Assume``."""
        lits = []
        for ident in identifiers:
            idx = self.problem.id_to_index.get(ident)
            if idx is None:
                raise InternalSolverError(
                    [f'variable "{ident}" referenced but not provided'])
            lits.append((idx + 1) if installed else -(idx + 1))
        self._scope_engine().assume(lits)

    def test(self) -> int:
        """Propagation-only check of the assumed scope — gini ``Test``.
        Returns 1 (sat by propagation), -1 (conflict), 0 (undetermined);
        pushes a scope that :meth:`untest` pops."""
        return self._scope_engine().test()

    def untest(self) -> int:
        """Pop the most recent :meth:`test` scope (gini ``Untest``);
        returns the remaining scope depth."""
        return self._scope_engine().untest()

    def assumptions(self) -> List[tuple]:
        """The open assumption stack as ``(identifier, installed)``
        pairs, in assumption order — empty when no scope is open.  The
        facade's scope-owner is the host engine's literal stack, so
        :meth:`untest` truncation is reflected here for free."""
        eng = getattr(self, "_inc_engine", None)
        if eng is None:
            return []
        vs = self.problem.variables
        return [(vs[abs(lit) - 1].identifier, lit > 0)
                for lit in eng._assumed_lits]

    def scope_depth(self) -> int:
        """Open :meth:`test` scopes (gini's scope depth)."""
        eng = getattr(self, "_inc_engine", None)
        return len(eng._test_scopes) if eng is not None else 0

    def scope_state(self) -> tuple:
        """``(assumptions, scopes, scope_base)`` — the full scope-stack
        state for serialization (ISSUE 20 drain/join handoff):
        ``assumptions`` as :meth:`assumptions` renders them, ``scopes``
        the engine's pushed scope bases, ``scope_base`` the current
        one.  Replayable through the public assume/test surface."""
        eng = getattr(self, "_inc_engine", None)
        if eng is None:
            return [], [], 0
        return (self.assumptions(), list(eng._test_scopes),
                int(eng._scope_base))

    def _scope_key(self, assumptions: Sequence[tuple]) -> str:
        """Session-local lane key for a scoped solve: the base problem's
        canonical fingerprint (paid ONCE per solver, memoized) salted
        with the open assumption stack in order.  Scoped lanes bypass
        the shared result cache in both directions, so this key's only
        job is entry identity inside the session's private clause-set
        index — which makes an O(assumptions) digest legitimate where
        stateless lanes must pay the O(problem) ``fingerprint``.
        Deterministic per (catalog, stack), so revisiting an assumption
        state revisits its private-index entry."""
        base = self.problem.__dict__.get("_scope_base_key")
        if base is None:
            from ..sched.cache import fingerprint

            base = fingerprint(self.problem)
            self.problem.__dict__["_scope_base_key"] = base
        h = hashlib.sha256(base.encode())
        for ident, installed in assumptions:
            h.update(b"\x1f" + str(ident).encode("utf-8", "surrogatepass"))
            h.update(b"+" if installed else b"-")
        return "scope:" + h.hexdigest()

    def _scope_plan_args(self, assumptions: Sequence[tuple]) -> tuple:
        """``(session_key, scope_entry_key, scope_seed)`` for
        ``Scheduler.submit_session``: this solve's session-local key,
        the previous scoped solve's key (the declared warm predecessor
        in the private index — None on the session's first solve), and
        the variable indices whose assumptions CHANGED between the two
        stacks (multiset symmetric difference, so a re-assumed pair
        cancels and an assume-then-invert shows up once per side) — the
        exact seed the O(delta) cone closure needs, because every
        added/removed constraint row is a unit on one of these
        subjects."""
        key = self._scope_key(assumptions)
        prev = getattr(self, "_scope_last", None)
        if prev is None:
            return key, None, ()
        prev_key, prev_assumptions = prev
        cur_c = Counter(assumptions)
        prev_c = Counter(prev_assumptions)
        seed = sorted({
            idx for ident, _ in
            list((cur_c - prev_c).keys()) + list((prev_c - cur_c).keys())
            if (idx := self.problem.id_to_index.get(ident)) is not None})
        return key, prev_key, tuple(seed)

    def solve_scoped(self, deadline_s=None, stats: Optional[dict] = None):
        """Solve under the OPEN assumption stack and return the raw
        result object (solution dict / ``NotSatisfiable`` /
        ``Incomplete`` — the scheduler-lane contract, un-decoded so a
        serving layer can render it byte-identically to ``/v1/resolve``).

        With a scheduler attached (ISSUE 20) the solve routes through
        ``Scheduler.submit_session``: dedicated session class, registry
        backends raced, deadlines/breaker/fair admission unchanged, the
        shared result cache bypassed in BOTH directions (an
        assumption-conditioned answer must never be admitted where
        stateless traffic could read it — satellite 2), and warm starts
        planned against ``self.warm_index`` when set — O(delta) against
        the previous scoped solve's entry when one is on record, the
        generic classifier otherwise.  Without one, the derived problem
        solves on the host spec engine inline — the same answer, no
        registry awareness.

        The derived problem is lowered via ``encode_assumed`` — the
        session IS the retained encoding, so the per-step lowering cost
        is the assumption splice, not a catalog re-walk (differential
        tests pin the splice byte-identical to a full ``encode``)."""
        assumptions = self.assumptions()
        p = encode_assumed(self.problem, assumptions)
        if self.scheduler is not None:
            key, entry_key, seed = self._scope_plan_args(assumptions)
            try:
                return self.scheduler.submit_session(
                    p.variables, deadline_s=deadline_s,
                    max_steps=self.max_steps, stats=stats,
                    tenant=self.tenant, warm_index=self.warm_index,
                    session_key=key, scope_entry_key=entry_key,
                    scope_seed=seed, problem=p)
            finally:
                # Track the key/stack pair even for UNSAT/degraded
                # answers: a missing private-index entry just means the
                # next step's scoped plan misses and the generic
                # classifier (then the cold path) answers.
                self._scope_last = (key, list(assumptions))
        if p.errors:
            raise InternalSolverError(p.errors)
        engine = HostEngine(p, max_steps=self.max_steps)
        try:
            installed, _ = engine.solve()
        except (NotSatisfiable, Incomplete) as e:
            if stats is not None:
                stats["steps"] = engine.steps
            return e
        finally:
            self.steps = engine.steps
        if stats is not None:
            stats["steps"] = engine.steps
        solution = {v.identifier: False for v in p.variables}
        for v in installed:
            solution[v.identifier] = True
        return solution

    def solve(self) -> List[Variable]:
        if self.assumptions():
            # ISSUE 20: a solve under an open scope answers for the
            # ASSUMED problem (gini's Solve consumes assumptions; the
            # pre-session facade silently ignored them).  Routed through
            # solve_scoped so a scheduler-attached solver gets registry
            # engines and the cache bypass; decoded back to the facade's
            # installed-variables contract.
            r = self.solve_scoped()
            if isinstance(r, (NotSatisfiable, Incomplete)):
                raise r
            return [v for v in self.problem.variables
                    if r.get(v.identifier)]
        backend = resolve_backend(self.backend, batch=False)
        if backend == "host":
            return self._solve_host()
        from ..engine.driver import solve_one

        stats: dict = {}
        try:
            return solve_one(self.problem, max_steps=self.max_steps,
                             stats=stats, tracer=self.tracer,
                             trace_cap=self.trace_cap)
        finally:
            self.steps = stats.get("steps", 0)
            self.report = stats.get("report")

    def _solve_host(self) -> List[Variable]:
        if self.tracer is not None:
            # Tracer callbacks can't cross a process boundary: a traced
            # solve stays on the in-process engine.
            return self._solve_host_traced()
        # The shared host-path entry (ISSUE 5): one lane through
        # deppy_tpu.hostpool, which routes a batch of one inline anyway
        # (a lone problem is IPC-latency-bound the same way it is
        # dispatch-latency-bound on the device) but keeps this facade on
        # the single solve_lane implementation the pool's differential
        # tests pin.
        from .. import hostpool

        try:
            (lane,) = hostpool.solve_host_problems(
                [self.problem], max_steps=self.max_steps)
        except InternalSolverError:
            # Parity with the engine path's finally: the report exists
            # (outcome-less) even when the problem was malformed.
            self.steps = 0
            self.report = telemetry.SolveReport(backend="host",
                                                n_problems=1)
            raise
        self.steps = lane.steps
        rep = telemetry.SolveReport(backend="host", n_problems=1)
        rep.count_outcome(lane.outcome)
        rep.steps = lane.steps
        rep.decisions = lane.decisions
        rep.propagation_rounds = lane.propagation_rounds
        rep.backtracks = lane.backtracks
        rep.add_wall("solve", lane.wall_s)
        self.report = rep
        if lane.outcome == "sat":
            return [self.problem.variables[i] for i in lane.installed_idx]
        if lane.outcome == "unsat":
            raise NotSatisfiable(
                [self.problem.applied[j] for j in lane.core_idx])
        raise Incomplete()

    def _solve_host_traced(self) -> List[Variable]:
        engine = HostEngine(
            self.problem, tracer=self.tracer, max_steps=self.max_steps
        )
        t0 = time.perf_counter()
        outcome: Optional[str] = None
        try:
            installed, _ = engine.solve()
            outcome = "sat"
            return installed
        except NotSatisfiable:
            outcome = "unsat"
            raise
        except Incomplete:
            outcome = "incomplete"
            raise
        finally:
            self.steps = engine.steps
            rep = telemetry.SolveReport(backend="host", n_problems=1)
            if outcome is not None:
                rep.count_outcome(outcome)
            rep.steps = engine.steps
            rep.decisions = engine.decisions
            rep.propagation_rounds = engine.propagation_rounds
            rep.backtracks = engine.backtracks
            rep.add_wall("solve", time.perf_counter() - t0)
            self.report = rep


def resolve_backend(backend: str, *, batch: bool = True,
                    block: bool = True) -> str:
    """Resolve a backend name to ``"host"`` or ``"tpu"``: the single place
    the ``auto`` policy lives (shared by :class:`Solver` and the resolution
    facade).  Raises on unknown names.

    ``batch=False`` marks a single-problem solve: ``auto`` picks the host
    engine there — a batch of one is dispatch-latency-bound and the serial
    host engine beats the device on every single-problem workload measured
    (BASELINE.md config 1: 67/s host vs 11/s device on the tunneled TPU).
    The tensor engine's win is batch parallelism; ``auto`` reserves it for
    batches.  Explicit ``"tpu"`` still forces the device path.

    An **open accelerator circuit breaker** (ISSUE 2: N consecutive
    device dispatch failures) also degrades ``auto`` to the host engine
    — without re-probing — until the breaker's cooldown elapses; the
    driver's half-open probe dispatch then decides whether device
    routing resumes.  Explicit ``"tpu"`` still resolves to the tensor
    *path* here, but it does not override the breaker: while it is open
    the driver's dispatch-level recovery host-routes every group (loud:
    ``deppy_fault_host_routed_total``, ``fault`` sink events), and the
    service refuses explicit-tpu requests outright with 503 +
    Retry-After.  Exact answers either way; device *timing* is only
    measurable with the breaker closed.

    ``block=False`` marks a caller that must not stall on the first-use
    engine probe (the request scheduler's dispatch loop: a 75s probe
    there would freeze every queued request behind it).  While no
    verdict exists yet — and the platform isn't pinned to CPU, where the
    in-process probe is instant — ``auto`` answers ``"host"`` instead of
    probing; the service's startup pre-warm (or any blocking caller)
    establishes the verdict and subsequent dispatches route normally."""
    if backend == "auto":
        if not batch:
            return "host"
        from .. import faults

        if faults.default_breaker().blocks_device():
            return "host"
        if (not block and _ENGINE_USABLE is None
                and (os.environ.get("JAX_PLATFORMS") or "").strip()
                != "cpu"):
            return "host"
        return "tpu" if _engine_usable() else "host"
    if backend in ("host", "tpu"):
        return backend
    raise InternalSolverError([f"unknown backend {backend!r}"])


_ENGINE_USABLE: Optional[bool] = None
# Serializes the probe: concurrent auto callers (e.g. requests hitting a
# service while its startup pre-warm is still probing) share one probe
# subprocess and its verdict instead of each spawning their own.
_ENGINE_USABLE_LOCK = threading.Lock()
# A healthy TPU PJRT init takes ~8s on this machine and the tiny probe
# compile a few more seconds over the tunnel; a crashed worker can hang
# init for minutes-to-hours (BASELINE.md round-3 notes), so the probe
# must be killable.
_PROBE_TIMEOUT_S = 75
# The child also self-destructs shortly after the parent's timeout, so an
# orphan (parent died mid-probe — e.g. a service restart while the
# pre-warm thread was probing) cannot hang in PJRT init for hours holding
# the runtime handle.
_PROBE_SELF_DESTRUCT_S = _PROBE_TIMEOUT_S + 5
# The probe must COMPUTE, not just init: a wedged worker can answer
# ``jax.devices()`` and then hang the first compile for 20+ minutes
# (observed 2026-07-31), which would wedge every auto-routed solve
# behind it.  platform_env.probe_src provides the shared init+compute
# source (SIGALRM self-destruct, os._exit to skip hangable PJRT
# teardown); the epilogue additionally proves the tensor engine imports.
def _probe_cmd_src() -> str:
    from ..utils.platform_env import probe_src

    return probe_src(
        _PROBE_SELF_DESTRUCT_S,
        epilogue="; import deppy_tpu.engine.driver",
    )


def reprobe_engine() -> bool:
    """Probe engine usability again and replace the cached verdict.

    The cached verdict makes ``auto`` a routing policy, not a health
    monitor — right for short-lived processes, wrong for a long-lived
    service that booted during an accelerator outage and would otherwise
    route to the host engine forever after the worker recovers.  The
    service's pre-warm loop calls this on an interval while the verdict
    is negative (see service.Service.start); anyone else running a
    long-lived auto-routed process can do the same.  Returns the fresh
    verdict.  Downgrades work too: a probe failing after a positive
    verdict flips routing back to host for subsequent solves.

    The stale verdict stays in place (and readable lock-free by
    ``_engine_usable``'s fast path) while the probe runs, so concurrent
    auto solves keep routing instantly instead of blocking up to the
    probe timeout; the fresh verdict swaps in atomically afterwards."""
    global _ENGINE_USABLE
    with _ENGINE_USABLE_LOCK:
        fresh = _probe_verdict()
        _ENGINE_USABLE = fresh
    if fresh:
        # A successful subprocess probe (init + compute + engine import)
        # is independent evidence the accelerator recovered: close the
        # circuit breaker so auto routing doesn't stay host-only for a
        # full cooldown after the worker comes back.
        from .. import faults

        faults.default_breaker().reset()
    return fresh


def _engine_usable() -> bool:
    """True when the tensor engine and a JAX backend are both usable.
    ``auto`` degrades to the host engine rather than failing, so the
    library stays usable on machines without a working accelerator
    runtime.

    When the platform is not pinned to CPU, the backend query runs in a
    killable SUBPROCESS with a timeout: a crashed TPU worker hangs PJRT
    init indefinitely, and an in-process ``jax.devices()`` would hang
    every ``auto`` caller with it (the long-running service's failure
    mode during a worker outage).  The verdict is cached for the process
    lifetime — ``auto`` is a routing policy, not a health monitor."""
    global _ENGINE_USABLE
    if _ENGINE_USABLE is not None:
        return _ENGINE_USABLE
    with _ENGINE_USABLE_LOCK:
        return _engine_usable_locked()


def _engine_usable_locked() -> bool:
    global _ENGINE_USABLE
    if _ENGINE_USABLE is not None:  # a concurrent caller probed first
        return _ENGINE_USABLE
    _ENGINE_USABLE = _probe_verdict()
    return _ENGINE_USABLE


def _probe_verdict() -> bool:
    """One engine-usability probe, no cache interaction (callers manage
    the ``_ENGINE_USABLE`` cache and its lock)."""
    try:
        from ..engine import driver  # noqa: F401
    # deppy: lint-ok[exception-hygiene] probe: an unusable engine import IS the False verdict
    except Exception:
        return False
    import os

    if (os.environ.get("JAX_PLATFORMS") or "").strip() == "cpu":
        # Forced-CPU never touches the accelerator plugin: safe in-process.
        try:
            import jax

            jax.devices()
            return True
        # deppy: lint-ok[exception-hygiene] probe: failure IS the False verdict
        except Exception:
            return False
    import subprocess
    import sys

    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH")) if p
    )
    try:
        # DEVNULL, not capture: with captured pipes a TimeoutExpired kills
        # only the direct child and then blocks on pipe EOF — a wedged
        # runtime helper process holding the pipe would re-hang the
        # parent, the exact failure this probe exists to bound.
        probe = subprocess.run(
            [sys.executable, "-c", _probe_cmd_src()],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            timeout=_PROBE_TIMEOUT_S,
            env=env,
        )
        return probe.returncode == 0
    # deppy: lint-ok[exception-hygiene] probe: a hung/failed spawn IS the False verdict
    except Exception:  # TimeoutExpired (hung init) or spawn failure
        return False
