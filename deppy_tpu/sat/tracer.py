"""Search observability hooks (reference /root/reference/pkg/sat/tracer.go).

A ``Tracer`` is invoked at every backtrack with the current search position:
the stack of guessed variables and the constraints implicated in the
conflict that forced the backtrack (tracer.go:13-15, search.go:172-173).
"""

from __future__ import annotations

from typing import IO, List, Protocol

from .constraints import AppliedConstraint, Variable


class SearchPosition(Protocol):
    """Snapshot of the search at a backtrack point (tracer.go:8-11)."""

    def variables(self) -> List[Variable]: ...

    def conflicts(self) -> List[AppliedConstraint]: ...


class Tracer(Protocol):
    def trace(self, position: SearchPosition) -> None: ...


class DefaultTracer:
    """No-op tracer (tracer.go:17-20)."""

    def trace(self, position: SearchPosition) -> None:
        pass


class LoggingTracer:
    """Writes a human-readable transcript of each backtrack
    (tracer.go:22-35); used by the conformance tests to dump failing
    searches the same way solve_test.go:352-354 does."""

    def __init__(self, writer: IO[str]):
        self.writer = writer

    def trace(self, position: SearchPosition) -> None:
        self.writer.write("---\nAssumptions:\n")
        for v in position.variables():
            self.writer.write(f"- {v.identifier}\n")
        self.writer.write("Conflicts:\n")
        for c in position.conflicts():
            self.writer.write(f"- {c}\n")


class StatsTracer:
    """Counts backtracks — the cheap always-on statistics channel the tensor
    engine also reports (decisions/conflicts/propagation rounds)."""

    def __init__(self) -> None:
        self.backtracks = 0

    def trace(self, position: SearchPosition) -> None:
        self.backtracks += 1
