"""Search observability hooks (reference /root/reference/pkg/sat/tracer.go).

A ``Tracer`` is invoked at every backtrack with the current search position:
the stack of guessed variables and the constraints implicated in the
conflict that forced the backtrack (tracer.go:13-15, search.go:172-173).
"""

from __future__ import annotations

from typing import IO, List, Protocol

from .constraints import AppliedConstraint, Variable


class SearchPosition(Protocol):
    """Snapshot of the search at a backtrack point (tracer.go:8-11)."""

    def variables(self) -> List[Variable]: ...

    def conflicts(self) -> List[AppliedConstraint]: ...


class Tracer(Protocol):
    def trace(self, position: SearchPosition) -> None: ...


class DefaultTracer:
    """No-op tracer (tracer.go:17-20)."""

    def trace(self, position: SearchPosition) -> None:
        pass


class LoggingTracer:
    """Writes a human-readable transcript of each backtrack
    (tracer.go:22-35); used by the conformance tests to dump failing
    searches the same way solve_test.go:352-354 does."""

    def __init__(self, writer: IO[str]):
        self.writer = writer

    def trace(self, position: SearchPosition) -> None:
        self.writer.write("---\nAssumptions:\n")
        for v in position.variables():
            self.writer.write(f"- {v.identifier}\n")
        self.writer.write("Conflicts:\n")
        for c in position.conflicts():
            self.writer.write(f"- {c}\n")


class StatsTracer:
    """Counts backtracks, decisions, and propagation rounds — the cheap
    always-on statistics channel matching the tensor engine's counters
    (SolveResult.steps / trace_n), so host-fallback solves contribute to
    the same telemetry as device solves.

    ``trace`` (the base Tracer protocol) counts search backtracks;
    ``count_decision`` / ``count_propagation`` are optional hook methods
    the host engine invokes when its tracer defines them — it is wired
    as the host engine's default tracer, so every host solve carries
    these counters without opting in.

    ``wants_position = False`` tells the engine this tracer never reads
    the position argument, so the per-backtrack position snapshot is
    skipped — the default tracer must not perturb the timed host
    baseline the benchmarks compare against."""

    wants_position = False

    def __init__(self) -> None:
        self.backtracks = 0
        self.decisions = 0
        self.propagation_rounds = 0

    def trace(self, position: SearchPosition) -> None:
        self.backtracks += 1

    def count_decision(self, n: int = 1) -> None:
        """One search/DPLL decision (a variable guessed, either by the
        preference-ordered search or the leaf DPLL)."""
        self.decisions += n

    def count_propagation(self, rounds: int = 1) -> None:
        """``rounds`` BCP fixpoint iterations completed."""
        self.propagation_rounds += rounds

    def as_dict(self) -> dict:
        return {
            "backtracks": self.backtracks,
            "decisions": self.decisions,
            "propagation_rounds": self.propagation_rounds,
        }
