"""Constraint vocabulary for the boolean-constraint solver.

Mirrors the five constraint types of the reference framework
(/root/reference/pkg/sat/constraints.go:54-204) with idiomatic Python
dataclasses instead of interface implementations.  A constraint limits the
circumstances under which a particular variable may appear in a solution.

Each constraint knows how to:
  * render itself as a human-readable string for a subject identifier
    (used by unsat-core error messages), and
  * report its preference ``order`` (non-empty only for ``Dependency``,
    reference constraints.go:125-127) and whether it ``anchors`` its subject
    into the search seed set (true only for ``Mandatory``,
    reference constraints.go:68-70).

Unlike the reference, constraints do not encode themselves into a logic
circuit; lowering to dense clause/cardinality tensors happens in
:mod:`deppy_tpu.sat.encode`, which is the TPU-friendly equivalent of
lit_mapping.go's two-pass construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple, Union

# An Identifier uniquely names a Variable within one solve
# (reference pkg/sat/variable.go:5-17).  Plain ``str`` is idiomatic here.
Identifier = str


@dataclass(frozen=True)
class Mandatory:
    """Only solutions containing the subject variable are permitted
    (reference constraints.go:54-76)."""

    def string(self, subject: Identifier) -> str:
        return f"{subject} is mandatory"

    def order(self) -> Tuple[Identifier, ...]:
        return ()

    def anchor(self) -> bool:
        return True


@dataclass(frozen=True)
class Prohibited:
    """Any solution containing the subject variable is rejected
    (reference constraints.go:78-102)."""

    def string(self, subject: Identifier) -> str:
        return f"{subject} is prohibited"

    def order(self) -> Tuple[Identifier, ...]:
        return ()

    def anchor(self) -> bool:
        return False


@dataclass(frozen=True)
class Dependency:
    """The subject may appear only if at least one of ``ids`` also appears.
    Identifiers earlier in ``ids`` are preferred over later ones
    (reference constraints.go:104-140)."""

    ids: Tuple[Identifier, ...]

    def string(self, subject: Identifier) -> str:
        if not self.ids:
            return f"{subject} has a dependency without any candidates to satisfy it"
        return f"{subject} requires at least one of {', '.join(self.ids)}"

    def order(self) -> Tuple[Identifier, ...]:
        return self.ids

    def anchor(self) -> bool:
        return False


@dataclass(frozen=True)
class Conflict:
    """The subject and ``id`` may not both appear in a solution
    (reference constraints.go:142-165)."""

    id: Identifier

    def string(self, subject: Identifier) -> str:
        return f"{subject} conflicts with {self.id}"

    def order(self) -> Tuple[Identifier, ...]:
        return ()

    def anchor(self) -> bool:
        return False


@dataclass(frozen=True)
class AtMost:
    """At most ``n`` of ``ids`` may appear in a solution
    (reference constraints.go:167-204).

    The reference lowers this through a sorting-network cardinality circuit
    (gini ``logic.CardSort``); here it lowers to a native cardinality row
    propagated directly by the tensor engine (see encode.py), which avoids
    the pointer-heavy network entirely.

    Deliberate divergence for degenerate input: duplicate ``ids`` are
    counted once ("at most n *distinct* members"), whereas gini's CardSort
    counts occurrences.  Set semantics keeps every engine path (host,
    gather, bitplane) in exact agreement; no reference behavior or test
    depends on multiset counting.
    """

    n: int
    ids: Tuple[Identifier, ...]

    def string(self, subject: Identifier) -> str:
        return f"{subject} permits at most {self.n} of {', '.join(self.ids)}"

    def order(self) -> Tuple[Identifier, ...]:
        return ()

    def anchor(self) -> bool:
        return False


Constraint = Union[Mandatory, Prohibited, Dependency, Conflict, AtMost]


def mandatory() -> Mandatory:
    """Constraint permitting only solutions that contain the subject."""
    return Mandatory()


def prohibited() -> Prohibited:
    """Constraint rejecting any solution that contains the subject."""
    return Prohibited()


def dependency(*ids: Identifier) -> Dependency:
    """Constraint requiring at least one of ``ids`` alongside the subject;
    earlier arguments are preferred (reference constraints.go:133-140)."""
    return Dependency(tuple(ids))


def conflict(id: Identifier) -> Conflict:
    """Constraint permitting the subject or ``id`` but not both."""
    return Conflict(id)


def at_most(n: int, *ids: Identifier) -> AtMost:
    """Constraint forbidding solutions with more than ``n`` of ``ids``."""
    return AtMost(n, tuple(ids))


@dataclass(frozen=True)
class Variable:
    """A problem variable: an identifier plus the constraints that apply to
    it (reference pkg/sat/variable.go:19-29).  Instances are immutable; use
    :func:`variable` to build one."""

    identifier: Identifier
    constraints: Tuple[Constraint, ...] = field(default_factory=tuple)


def variable(identifier: Identifier, *constraints: Constraint) -> Variable:
    """Convenience constructor mirroring the reference test helper
    (solve_test.go:32-37) and pkg/constraints/variable.go:25-30."""
    return Variable(identifier, tuple(constraints))


@dataclass(frozen=True)
class AppliedConstraint:
    """A constraint paired with the variable it applies to, used in
    unsat-core reporting (reference constraints.go:41-52)."""

    variable: Variable
    constraint: Constraint

    def __str__(self) -> str:
        return self.constraint.string(self.variable.identifier)
