"""Error types for the solver layer.

Mirrors the error surface of the reference (pkg/sat/solve.go:14-30,
lit_mapping.go:12-22) as Python exceptions.
"""

from __future__ import annotations

from typing import List, Sequence

from .constraints import AppliedConstraint, Identifier


class NotSatisfiable(Exception):
    """Raised when no solution exists.  Carries a minimal set of applied
    constraints sufficient to make a solution impossible
    (reference solve.go:16-30).

    The message format matches the reference exactly:
    ``constraints not satisfiable: a is mandatory, a is prohibited``.
    """

    def __init__(self, constraints: Sequence[AppliedConstraint] = ()):
        self.constraints: List[AppliedConstraint] = list(constraints)
        super().__init__(self._message())

    def _message(self) -> str:
        msg = "constraints not satisfiable"
        if not self.constraints:
            return msg
        return f"{msg}: {', '.join(str(c) for c in self.constraints)}"

    def __str__(self) -> str:
        return self._message()


class DuplicateIdentifier(Exception):
    """Raised at solver construction when two input variables share an
    identifier (reference lit_mapping.go:12-16, solve_test.go:359-365)."""

    def __init__(self, identifier: Identifier):
        self.identifier = identifier
        super().__init__(f'duplicate identifier "{identifier}" in input')


class Incomplete(Exception):
    """Raised when the solve is cancelled (deadline/iteration budget) before
    a definitive answer is found (reference solve.go:14).  Unlike the
    reference — whose search never actually honors its context
    (solve.go:83 passes context.Background()) — the rebuilt engine enforces
    an iteration budget so hung searches surface as this error."""

    def __init__(self, message: str = "cancelled before a solution could be found"):
        super().__init__(message)


class InternalSolverError(Exception):
    """Aggregated internal-consistency failures, e.g. a constraint
    referencing an identifier that was never provided as a variable
    (reference lit_mapping.go:18-22,81-88,115-128)."""

    def __init__(self, errors: Sequence[str]):
        self.errors = list(errors)
        super().__init__(
            f"{len(self.errors)} errors encountered: {', '.join(self.errors)}"
        )


class BackendCapabilityError(Exception):
    """A requested solve path needs an engine capability the currently
    selected backend/impl does not provide (e.g. clause sharding, which
    carries its per-round OR collective only in the ``bits`` BCP round
    kernel).  Distinct from :class:`InternalSolverError` — the input is
    fine; it is the *configuration* that cannot serve it — so callers
    (the facade, the service) can render it as a clean client-actionable
    error instead of an internal failure."""

    def __init__(self, capability: str, selected: str, hint: str = ""):
        self.capability = capability
        self.selected = selected
        msg = (f"backend capability {capability!r} unavailable "
               f"(selected: {selected!r})")
        if hint:
            msg += f": {hint}"
        super().__init__(msg)
