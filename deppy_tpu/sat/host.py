"""Host (CPU/NumPy) reference engine.

This is the executable semantic specification of the solve algorithm — the
rebuild's stand-in for gini + the reference's search driver.  The TPU tensor
engine (:mod:`deppy_tpu.engine`) implements the *same* algorithm with dense
fixed-shape state inside ``lax.while_loop``; differential tests assert the
two agree bit-for-bit on outcomes, installed sets, and unsat cores.

Algorithm (mirroring /root/reference/pkg/sat/solve.go:53-119 and
search.go:34-203):

1. assume every constraint's activation + every anchor (solve.go:67-75) and
   run a baseline propagation "Test" (solve.go:79);
2. if undetermined, run the preference-ordered guess search: a deque of
   choices (anchor singletons, then Dependency candidate lists pushed when
   their subject is guessed), depth-first with chronological backtracking
   that retries the next candidate of a failed choice (search.go:34-98);
3. on SAT, cardinality-minimize only the "extras" — model-true variables
   that were never guessed — holding guesses true and model-false variables
   false (solve.go:86-113);
4. on UNSAT, report a minimal core of applied constraints
   (solve.go:114-115) computed by deletion-based minimization over
   activation assumptions (the engine-agnostic analog of gini's ``Why``).

Propagation ("Test", gini inter.S.Test) is a dense boolean-constraint
propagation to fixpoint over the clause matrix plus native cardinality rows;
full "Solve" (gini CDCL, search.go:168) is DPLL with false-first polarity on
the lowest-index unassigned variable, which doubles as a
minimal-model-biased completion.
"""

from __future__ import annotations

from collections import deque as _deque
from dataclasses import dataclass
from typing import List, Optional, Sequence, Set, Tuple

import numpy as np

from .constraints import AppliedConstraint, Variable
from .encode import Problem
from .errors import Incomplete, InternalSolverError, NotSatisfiable
from .tracer import SearchPosition, StatsTracer, Tracer

SAT = 1
UNSAT = -1
UNKNOWN = 0

_UNASSIGNED = 0
_TRUE = 1
_FALSE = -1


class WarmStartConflict(Exception):
    """A warm-started solve could not certify byte-identity to a cold
    solve and must fall back (ISSUE 10).

    Raised by :meth:`HostEngine.solve_warm` whenever the cached
    assignment prefix conflicts with the delta problem, the cone search
    needs a backtrack (certification requires a conflict-free cone
    walk), or any other precondition of the warm/cold equivalence
    argument fails.  This is control flow, not an error: the caller
    answers with a cold solve and the result stays exact."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class GuidanceUnverified(Exception):
    """A gradient-guided solve could not certify byte-identity to
    :meth:`HostEngine.solve` and must fall back (ISSUE 13).

    Raised by :meth:`HostEngine.solve_guided` whenever the rounded
    relaxation fails its BCP verification pass, the problem's baseline
    is UNSAT (cores stay the discrete engines' business), or the
    zero-backtrack completion walk would need real backtracking.  Like
    :class:`WarmStartConflict` this is control flow, not an error: the
    portfolio racer answers with a discrete engine and the result
    stays exact."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class SolveCancelled(Exception):
    """A cooperatively-cancelled solve (ISSUE 13 portfolio racing).

    Raised from :meth:`HostEngine._count_step` when the engine's
    ``cancel`` event is set: a race's losing host lane stops at the
    next step boundary instead of running to completion.  Never a
    solve verdict — the racer discards the lane entirely."""


@dataclass
class _Guess:
    """One entry of the guess stack (reference search.go:16-21)."""

    choice: int                 # choice-table row
    index: int                  # candidate index guessed (or where search stopped)
    var: int                    # guessed var, or -1 if the choice was null/satisfied
    children: int               # choices spawned by this guess


class _Position(SearchPosition):
    def __init__(self, variables: List[Variable], conflicts: List[AppliedConstraint]):
        self._variables = variables
        self._conflicts = conflicts

    def variables(self) -> List[Variable]:
        return self._variables

    def conflicts(self) -> List[AppliedConstraint]:
        return self._conflicts


# Shared sentinel handed to stats-only tracers (wants_position = False):
# they count the call and never look inside.
_EMPTY_POSITION = _Position([], [])


class HostEngine:
    """Reference engine over a lowered :class:`Problem`."""

    def __init__(
        self,
        problem: Problem,
        tracer: Optional[Tracer] = None,
        max_steps: Optional[int] = None,
        cancel=None,
    ):
        self.p = problem
        # Cooperative cancellation (ISSUE 13): any object with
        # ``is_set()`` (a ``threading.Event``).  Checked at step
        # boundaries only — a race's losing lane stops at the next
        # step, never mid-propagation.  None (the default) keeps the
        # hot path free of the check's branch.
        self._cancel = cancel
        # StatsTracer is the default tracer (SURVEY.md §5): every host
        # solve — including the driver's host-fallback rows — counts
        # decisions/propagation rounds/backtracks into the same channel
        # the tensor engine reports, at the cost of three int adds.
        self.tracer = tracer if tracer is not None else StatsTracer()
        self.max_steps = max_steps
        self._steps = 0
        # Engine-side counters, always maintained (a custom tracer may
        # not implement the optional count_* hooks).
        self.decisions = 0
        self.propagation_rounds = 0
        self.backtracks = 0
        self._hook_decision = getattr(self.tracer, "count_decision", None)
        self._hook_propagation = getattr(
            self.tracer, "count_propagation", None
        )
        # Stats-only tracers (wants_position = False) skip the
        # per-backtrack position snapshot entirely, so wiring StatsTracer
        # as the default adds only integer increments to the hot path.
        self._trace_wants_position = getattr(
            self.tracer, "wants_position", True
        )

        p = problem
        self.n = p.n_vars
        self.v = p.n_total
        # Precompute clause index/sign planes for vectorized propagation.
        cls = p.clauses
        self._cls_mask = cls != 0
        self._cls_var = np.where(self._cls_mask, np.abs(cls) - 1, 0)
        self._cls_sign = np.sign(cls).astype(np.int8)
        card = p.card_ids
        self._card_mask = card >= 0
        self._card_var = np.where(self._card_mask, card, 0)
        # Base assignment: all activation vars true (AssumeConstraints,
        # lit_mapping.go:136-140).
        self._base = np.zeros(self.v, dtype=np.int8)
        if p.n_cons:
            self._base[self.n :] = _TRUE
        self.last_conflicts: List[AppliedConstraint] = []
        # Incremental assumption scopes (ISSUE 10): the gini
        # Assume/Test/Untest surface (reference solve.go:79,99,104 —
        # inter.S).  ``_assumed_lits`` is the flat signed-literal
        # assumption set; each Test scope OWNS the assumptions added
        # since the previous Test, so ``_test_scopes`` records each
        # scope's START offset (``_scope_base`` = the offset the next
        # scope will start at) and Untest deletes from there.
        self._assumed_lits: List[int] = []
        self._test_scopes: List[int] = []
        self._scope_base = 0

    @property
    def steps(self) -> int:
        """Engine iterations consumed so far (tests, decisions, backtracks) —
        the host-side counterpart of the tensor engine's SolveResult.steps
        (SURVEY.md §5 observability)."""
        return self._steps

    # ------------------------------------------------------------------ BCP

    def _conflict_cons(self, idx) -> None:
        """Record a BCP conflict's applied-constraint indices as rendered
        conflicts for the tracer/`Why` path."""
        idx = np.atleast_1d(np.asarray(idx, dtype=np.int64))
        self.last_conflicts = [self.p.applied[j] for j in idx]

    def _bcp(
        self,
        assign: np.ndarray,
        min_mask: Optional[np.ndarray] = None,
        min_w: int = 0,
        obj_w: Optional[np.ndarray] = None,
        obj_bound: int = 0,
    ) -> Tuple[bool, np.ndarray]:
        """Propagate to fixpoint.  Returns (conflict, assignment).

        One round evaluates every clause and cardinality row simultaneously —
        the dense analog of watched-literal BCP, and the op the TPU engine
        turns into a vmapped kernel.  ``min_mask``/``min_w`` is the dynamic
        "at most w of the extras" side-constraint used by the minimization
        loop (the native replacement for CardinalityConstrainer + Leq(w),
        solve.go:100-110).  ``obj_w``/``obj_bound`` (ISSUE 18) is the
        signed generalization the optimize tier's bound-tightening
        probes use: sum(obj_w[v] for model-true v) <= obj_bound, where a
        negative weight models a cost-when-false term (keep-installed)
        folded to signed form — unit positive weights over a mask
        degenerate to exactly the ``min_mask`` rule.
        """
        self._bcp_rounds = 0
        try:
            return self._bcp_loop(assign, min_mask, min_w, obj_w,
                                  obj_bound)
        finally:
            # Telemetry (SURVEY.md §5): every fixpoint iteration counts,
            # whichever of the loop's return paths ended it.
            self.propagation_rounds += self._bcp_rounds
            if self._hook_propagation is not None:
                self._hook_propagation(self._bcp_rounds)

    def _bcp_loop(
        self,
        assign: np.ndarray,
        min_mask: Optional[np.ndarray],
        min_w: int,
        obj_w: Optional[np.ndarray] = None,
        obj_bound: int = 0,
    ) -> Tuple[bool, np.ndarray]:
        p = self.p
        self.last_conflicts = []
        while True:
            self._bcp_rounds += 1
            # Cooperative cancel, per propagation round (ISSUE 13): the
            # minimization sweep's conflict-probing BCP passes are the
            # engine's dominant cost on deep chains and never reach
            # _count_step — a losing race lane must stop here, not
            # minutes later.
            if self._cancel is not None and self._cancel.is_set():
                raise SolveCancelled()
            changed = False
            conflict = False
            want = np.zeros(self.v, dtype=np.int8)  # pending implications

            if p.clauses.shape[0]:
                vals = assign[self._cls_var] * self._cls_sign
                vals = np.where(self._cls_mask, vals, _FALSE)
                sat_c = (vals == _TRUE).any(axis=1)
                unass = (vals == _UNASSIGNED).sum(axis=1)
                dead = ~sat_c & (unass == 0)
                if dead.any():
                    self._conflict_cons(p.clause_con[np.nonzero(dead)[0]])
                    return True, assign
                units = ~sat_c & (unass == 1)
                if units.any():
                    rows = np.nonzero(units)[0]
                    cols = np.argmax(vals[rows] == _UNASSIGNED, axis=1)
                    uvars = self._cls_var[rows, cols]
                    usigns = self._cls_sign[rows, cols]
                    for uv, us in zip(uvars, usigns):
                        if want[uv] != 0 and want[uv] != us:
                            self._conflict_cons(p.clause_con[rows])
                            return True, assign
                        want[uv] = us

            if p.card_ids.shape[0]:
                mvals = assign[self._card_var]
                trues = ((mvals == _TRUE) & self._card_mask).sum(axis=1)
                unk = ((mvals == _UNASSIGNED) & self._card_mask).sum(axis=1)
                active = assign[p.card_act] == _TRUE
                over = active & (trues > p.card_n)
                if over.any():
                    self._conflict_cons(p.card_con[np.nonzero(over)[0]])
                    return True, assign
                full = active & (trues == p.card_n) & (unk > 0)
                for r in np.nonzero(full)[0]:
                    for m in p.card_ids[r]:
                        if m >= 0 and assign[m] == _UNASSIGNED:
                            if want[m] == _TRUE:
                                self._conflict_cons(p.card_con[r])
                                return True, assign
                            want[m] = _FALSE

            if min_mask is not None:
                mvals = assign[: self.n]
                trues = int(((mvals == _TRUE) & min_mask).sum())
                unk_sel = (mvals == _UNASSIGNED) & min_mask
                if trues > min_w:
                    return True, assign
                if trues == min_w and unk_sel.any():
                    for m in np.nonzero(unk_sel)[0]:
                        if want[m] == _TRUE:
                            return True, assign
                        want[m] = _FALSE

            if obj_w is not None:
                mvals = assign[: self.n]
                unk_m = mvals == _UNASSIGNED
                neg = obj_w < 0
                # Least achievable objective under this prefix:
                # decided-true weights are spent, and every still-open
                # negative weight is free to take.  Like the min_mask
                # rule, a violated bound is a conflict with no applied
                # constraint to blame (it is a side constraint).
                lb = int(obj_w[mvals == _TRUE].sum()
                         + obj_w[unk_m & neg].sum())
                if lb > obj_bound:
                    return True, assign
                if unk_m.any():
                    # Forcing: an open positive-weight var the bound
                    # cannot afford must be false; an open negative-
                    # weight var whose refusal would break the bound
                    # must be true (lb already banks its weight).
                    for m in np.nonzero(unk_m & (obj_w > 0)
                                        & (obj_w + lb > obj_bound))[0]:
                        if want[m] == _TRUE:
                            return True, assign
                        want[m] = _FALSE
                    for m in np.nonzero(unk_m & neg
                                        & (lb - obj_w > obj_bound))[0]:
                        if want[m] == _FALSE:
                            return True, assign
                        want[m] = _TRUE

            pending = want != 0
            new = pending & (assign == _UNASSIGNED)
            clash = pending & (assign != _UNASSIGNED) & (assign != want)
            if clash.any():
                return True, assign
            if not new.any():
                return False, assign
            assign = assign.copy()
            assign[new] = want[new]

    # ----------------------------------------------------------------- Test

    def _test(
        self,
        guessed: Sequence[int],
        extra_true: Sequence[int] = (),
        extra_false: Sequence[int] = (),
        anchors_assumed: bool = True,
        act_enabled: Optional[np.ndarray] = None,
    ) -> Tuple[int, np.ndarray]:
        """Propagation-only check of the current assumption set — the analog
        of gini's ``Test`` (inter.S; used at solve.go:79, search.go:76).
        Returns SAT only when propagation alone yields a total assignment."""
        self._count_step()
        assign = self._base.copy()
        if act_enabled is not None:
            assign[self.n :] = np.where(act_enabled, _TRUE, _UNASSIGNED)
        if anchors_assumed:
            assign[self.p.anchors] = _TRUE
        for m in guessed:
            assign[m] = _TRUE
        for m in extra_true:
            assign[m] = _TRUE
        for m in extra_false:
            assign[m] = _FALSE
        conflict, assign = self._bcp(assign)
        if conflict:
            return UNSAT, assign
        if (assign[: self.n] != _UNASSIGNED).all():
            return SAT, assign
        return UNKNOWN, assign

    # ----------------------------------------------------------------- DPLL

    def _dpll(
        self,
        fixed_true: Sequence[int] = (),
        fixed_false: Sequence[int] = (),
        anchors_assumed: bool = True,
        act_enabled: Optional[np.ndarray] = None,
        min_mask: Optional[np.ndarray] = None,
        min_w: int = 0,
        obj_w: Optional[np.ndarray] = None,
        obj_bound: int = 0,
    ) -> Tuple[bool, Optional[np.ndarray]]:
        """Complete search under assumptions — the analog of gini ``Solve()``
        (search.go:168, solve.go:107).  Chronological DPLL, deciding the
        lowest-index unassigned problem variable false first, so discovered
        models are biased toward minimal installs before the explicit
        cardinality-minimization pass.  The false-first / lowest-index order
        also makes the returned model the lexicographically least model
        (false < true over var index), which the optimize tier relies on as
        its canonical tie-break."""
        assign = self._base.copy()
        if act_enabled is not None:
            assign[self.n :] = np.where(act_enabled, _TRUE, _UNASSIGNED)
        if anchors_assumed:
            assign[self.p.anchors] = _TRUE
        for m in fixed_true:
            assign[m] = _TRUE
        for m in fixed_false:
            assign[m] = _FALSE

        conflict, assign = self._bcp(assign, min_mask, min_w, obj_w, obj_bound)
        if conflict:
            return False, None
        # stack of (var, phase_tried_second, snapshot)
        stack: List[Tuple[int, bool, np.ndarray]] = []
        while True:
            self._count_step()
            unassigned = np.nonzero(assign[: self.n] == _UNASSIGNED)[0]
            if unassigned.size == 0:
                return True, assign
            var = int(unassigned[0])
            self._count_decision()
            stack.append((var, False, assign))
            trial = assign.copy()
            trial[var] = _FALSE
            conflict, trial = self._bcp(trial, min_mask, min_w, obj_w, obj_bound)
            while conflict:
                # Backtrack chronologically: flip the deepest unflipped
                # decision to true; pop flipped ones.
                while stack and stack[-1][1]:
                    stack.pop()
                if not stack:
                    return False, None
                var, _, snap = stack.pop()
                stack.append((var, True, snap))
                trial = snap.copy()
                trial[var] = _TRUE
                conflict, trial = self._bcp(trial, min_mask, min_w, obj_w, obj_bound)
            assign = trial

    # --------------------------------------------------------------- search

    def solve(self) -> Tuple[List[Variable], List[int]]:
        """Run the full algorithm.  Returns (installed variables in input
        order, installed indices).  Raises NotSatisfiable / Incomplete /
        InternalSolverError like the reference's error contract
        (solve.go:53-119)."""
        p = self.p
        if p.errors:
            raise InternalSolverError(p.errors)

        outcome, assign = self._test(guessed=())
        model: Optional[np.ndarray] = assign if outcome == SAT else None
        guessed_order: List[int] = []
        guessed: Set[int] = set()

        if outcome == UNKNOWN:
            outcome, guessed_order, model = self._search()
            guessed = set(guessed_order)
        elif outcome == SAT:
            # Search skipped: the baseline anchors play the role of the
            # guess set for minimization purposes (solve.go:77-83 keeps the
            # anchor assumptions when search doesn't run).
            guessed = set(int(x) for x in p.anchors)

        if outcome == SAT:
            assert model is not None
            return self._minimize(model, guessed)
        if outcome == UNSAT:
            raise NotSatisfiable(self._unsat_core())
        raise Incomplete()

    def _search(self) -> Tuple[int, List[int], Optional[np.ndarray]]:
        """Preference-ordered guess search (reference search.go:158-203)."""
        p = self.p
        dq: _deque = _deque()
        for r in range(len(p.anchors)):
            dq.append((r, 0))  # anchor choice rows come first in the table
        guesses: List[_Guess] = []
        result = UNKNOWN
        model: Optional[np.ndarray] = None

        def assumed_vars() -> List[int]:
            return [g.var for g in guesses if g.var >= 0]

        while True:
            if not dq and result == UNKNOWN:
                ok, m = self._dpll(fixed_true=assumed_vars())
                result = SAT if ok else UNSAT
                if ok:
                    model = m

            if result == UNSAT:
                self.backtracks += 1
                if self.tracer is not None:
                    self.tracer.trace(
                        _Position(
                            [p.variables[g.var] for g in guesses if g.var >= 0],
                            list(self.last_conflicts),
                        )
                        if self._trace_wants_position
                        else _EMPTY_POSITION
                    )
                if not guesses:
                    break
                # PopGuess (search.go:79-98): drop children from the back,
                # requeue the choice at the front advancing its candidate.
                g = guesses.pop()
                for _ in range(g.children):
                    dq.pop()
                dq.appendleft((g.choice, g.index + (1 if g.var >= 0 else 0)))
                if g.var >= 0:
                    result, assign = self._test(guessed=assumed_vars())
                    if result == SAT:
                        model = assign
                continue

            if not dq:
                break  # satisfiable and no decisions left (search.go:182-184)

            # PushGuess (search.go:34-77).
            cid, idx = dq.popleft()
            cands = [int(c) for c in p.choice_cand[cid] if c >= 0]
            var = cands[idx] if idx < len(cands) else -1
            assumed = set(assumed_vars())
            if any(c in assumed for c in cands):
                var = -1  # choice already satisfied by an assumption
            g = _Guess(choice=cid, index=idx, var=var, children=0)
            guesses.append(g)
            if var < 0:
                continue
            self._count_decision()
            for ch in p.var_choices[var] if var < len(p.var_choices) else []:
                if ch >= 0:
                    g.children += 1
                    dq.append((int(ch), 0))
            result, assign = self._test(guessed=assumed_vars())
            if result == SAT:
                model = assign

        return result, assumed_vars(), model

    # ------------------------------------------- incremental (ISSUE 10)
    #
    # Two entries sit on top of the cold pipeline above:
    #
    #   * assume/test/untest — the gini incremental-scope surface
    #     (reference solve.go:79,99,104): push assumption literals, run
    #     a propagation-only Test under them, pop the scope.
    #   * solve_warm — the delta warm-start entry: seed the assignment
    #     from a cached model restricted to the untouched cone
    #     complement, re-run search/completion/minimization over the
    #     cone only, and raise WarmStartConflict the moment the run
    #     leaves the regime where warm output provably equals cold
    #     output (any UNSAT test — i.e. any would-be backtrack — or a
    #     conflicting warm prefix).
    #
    # The equivalence argument solve_warm certifies at runtime: the cone
    # is closed under clause/cardinality adjacency, so the problem
    # decomposes into an untouched component (where the cached final
    # model is reproduced verbatim) and the cone component (re-solved
    # cold-style).  Chronological DPLL with the lowest-index/false-first
    # policy returns the lexicographically least model of each
    # independent component, and extras-minimization distributes over
    # components (the global minimum is the sum of component minima, and
    # the lex-least global optimum is the product of component optima) —
    # so as long as no search backtrack occurs in either the cached
    # solve or the cone walk, splicing cached-off-cone with cold-on-cone
    # IS the cold answer.  Any backtrack voids the argument → fallback.

    def assume(self, lits: Sequence[int]) -> None:
        """Add signed 1-based literals to the current assumption set
        (``v+1`` assumes variable ``v`` true, ``-(v+1)`` false) — the
        analog of gini ``Assume``.  Consumed by the next :meth:`test`."""
        for lit in lits:
            if lit == 0 or abs(int(lit)) > self.v:
                raise InternalSolverError(
                    [f"assumption literal {lit} out of range"])
            self._assumed_lits.append(int(lit))

    def test(self) -> int:
        """Propagation-only check of the accumulated assumptions — the
        analog of gini ``Test``: pushes a scope owning every assumption
        added since the previous Test, and returns ``SAT`` / ``UNSAT``
        / ``UNKNOWN`` (SAT only when propagation alone yields a total
        assignment)."""
        # The scope STARTS where the previous one ended — recording the
        # current length instead would make untest() a no-op for the
        # very assumptions this Test evaluated (review-caught).
        self._test_scopes.append(self._scope_base)
        self._scope_base = len(self._assumed_lits)
        outcome, _ = self._test(
            guessed=(),
            extra_true=[lit - 1 for lit in self._assumed_lits if lit > 0],
            extra_false=[-lit - 1 for lit in self._assumed_lits if lit < 0],
        )
        return outcome

    def untest(self) -> int:
        """Pop the most recent :meth:`test` scope, dropping the
        assumptions it owned — the analog of gini ``Untest``.  Returns
        the remaining scope depth."""
        if not self._test_scopes:
            raise InternalSolverError(["untest without a matching test"])
        self._scope_base = self._test_scopes.pop()
        del self._assumed_lits[self._scope_base:]
        return len(self._test_scopes)

    def solve_warm(
        self, warm_assign: np.ndarray, cone_mask: np.ndarray
    ) -> Tuple[List[Variable], List[int]]:
        """Warm-started solve: ``warm_assign`` (int8[n_vars], the cached
        final model as _TRUE/_FALSE) seeds every variable OUTSIDE
        ``cone_mask``; search, completion, and extras-minimization run
        over the cone only.  Returns exactly what :meth:`solve` returns
        on success; raises :class:`WarmStartConflict` whenever identity
        to a cold solve cannot be certified (the caller falls back)."""
        p = self.p
        if p.errors:
            raise InternalSolverError(p.errors)
        cone = np.asarray(cone_mask, dtype=bool)
        off = ~cone
        warm = np.asarray(warm_assign, dtype=np.int8)
        off_true = [int(i) for i in np.nonzero(off & (warm == _TRUE))[0]]
        off_false = [int(i) for i in np.nonzero(off & (warm != _TRUE))[0]]

        # Cold's own first step: a baseline that decides by propagation
        # alone takes a different (cheap) cold pipeline — fall back.
        outcome, _ = self._test(guessed=())
        if outcome != UNKNOWN:
            raise WarmStartConflict("baseline-decided")
        # The warm prefix: cached off-cone values must propagate without
        # conflict.  A conflict here is the chaos case — a stale or
        # poisoned cached model — and engages the cold fallback.
        outcome, _ = self._test(guessed=(), extra_true=off_true,
                                extra_false=off_false)
        if outcome == UNSAT:
            raise WarmStartConflict("warm-prefix-conflict")

        result, guessed_order, model = self._search_warm(
            off_true, off_false, cone)
        if result != SAT or model is None:
            raise WarmStartConflict("cone-search-conflict")
        return self._minimize_warm(model, set(guessed_order),
                                   off_true, off_false, cone)

    def _search_warm(
        self, off_true: List[int], off_false: List[int],
        cone: np.ndarray,
    ) -> Tuple[int, List[int], Optional[np.ndarray]]:
        """The preference-ordered guess search of :meth:`_search`,
        restricted to the cone component: only cone anchors seed the
        deque (their spawned choices are cone-closed), every Test runs
        under the warm off-cone prefix, and ANY UNSAT result aborts —
        zero backtracks is the certification condition, so the cold
        backtracking machinery is deliberately absent."""
        p = self.p
        dq: _deque = _deque()
        for r in range(len(p.anchors)):
            if cone[int(p.anchors[r])]:
                dq.append((r, 0))
        guesses: List[_Guess] = []
        result = UNKNOWN
        model: Optional[np.ndarray] = None

        def assumed_vars() -> List[int]:
            return [g.var for g in guesses if g.var >= 0]

        while True:
            if not dq and result == UNKNOWN:
                ok, m = self._dpll(fixed_true=assumed_vars() + off_true,
                                   fixed_false=off_false)
                result = SAT if ok else UNSAT
                if ok:
                    model = m
            if result == UNSAT:
                return UNSAT, assumed_vars(), None
            if not dq:
                break
            cid, idx = dq.popleft()
            cands = [int(c) for c in p.choice_cand[cid] if c >= 0]
            var = cands[idx] if idx < len(cands) else -1
            assumed = set(assumed_vars())
            if any(c in assumed for c in cands):
                var = -1
            g = _Guess(choice=cid, index=idx, var=var, children=0)
            guesses.append(g)
            if var < 0:
                continue
            self._count_decision()
            for ch in p.var_choices[var] if var < len(p.var_choices) else []:
                if ch >= 0:
                    g.children += 1
                    dq.append((int(ch), 0))
            result, assign = self._test(guessed=assumed_vars(),
                                        extra_true=off_true,
                                        extra_false=off_false)
            if result == SAT:
                model = assign
        return result, assumed_vars(), model

    def _minimize_warm(
        self, model: np.ndarray, guessed: Set[int],
        off_true: List[int], off_false: List[int], cone: np.ndarray,
    ) -> Tuple[List[Variable], List[int]]:
        """Extras-minimization over the cone component only: off-cone
        variables stay pinned at their cached (already-minimal) values,
        so the sweep's ``w`` range is the cone's extra count, not the
        problem's."""
        p = self.p
        extras = [
            i for i in range(self.n)
            if cone[i] and model[i] == _TRUE and i not in guessed
        ]
        excluded = [
            i for i in range(self.n)
            if cone[i] and model[i] != _TRUE and i not in guessed
        ]
        min_mask = np.zeros(self.n, dtype=bool)
        min_mask[extras] = True
        fixed_true = sorted(set(guessed) | set(off_true))
        fixed_false = excluded + off_false
        for w in range(len(extras) + 1):
            ok, m2 = self._dpll(
                fixed_true=fixed_true,
                fixed_false=fixed_false,
                min_mask=min_mask,
                min_w=w,
            )
            if ok:
                assert m2 is not None
                installed_idx = [i for i in range(self.n) if m2[i] == _TRUE]
                return [p.variables[i] for i in installed_idx], installed_idx
        # Cold minimization failing is an InternalSolverError; a WARM
        # sweep failing just means the certification regime broke —
        # answer cold instead of guessing.
        raise WarmStartConflict("cone-minimization-failed")

    # ------------------------------------------------- guided (ISSUE 13)
    #
    # The gradient-relaxation entrant's certification surface.  The
    # continuous descent (engine/grad_relax.py) proposes a rounded
    # assignment; this entry serves an answer ONLY when that answer is
    # provably the one :meth:`solve` would produce, and raises
    # :class:`GuidanceUnverified` the moment that proof breaks — the
    # portfolio racer then falls back to the discrete engines, so
    # correctness never depends on the heuristic.
    #
    # The equivalence argument, case by case:
    #
    #   * baseline-SAT (propagation from the base assumptions alone
    #     yields a total assignment): every variable is BCP-forced, so
    #     the extras-minimization sweep can only return that exact
    #     fixpoint (each w < n_extras conflicts on the forced trues;
    #     w = n_extras reproduces it) — serving the fixpoint directly
    #     is byte-identical while skipping the O(extras) sweep.  This
    #     is the deep-implication-chain class where lockstep DPLL
    #     burns whole-batch trips (the `hard` bench workload).
    #   * baseline-UNKNOWN: the rounded relaxation is first verified by
    #     one BCP pass (assume every variable at its rounded polarity;
    #     SAT means the rounding is a genuine model — a satisfiability
    #     certificate).  Then the preference-ordered guess search and
    #     the completion DPLL re-run exactly as :meth:`solve` would,
    #     except ANY would-be backtrack aborts (the solve_warm
    #     zero-backtrack discipline; _dpll_guided allows the one
    #     immediate false→true flip canonical DPLL performs in place).
    #     A run that never backtracks IS the canonical run, so the
    #     model — and the canonical `_minimize` that follows — match
    #     byte for byte.
    #   * baseline-UNSAT: unsat cores stay the discrete engines'
    #     business — always unverified.

    def solve_guided(
        self, hint_model: Optional[np.ndarray] = None
    ) -> Tuple[List[Variable], List[int]]:
        """Serve :meth:`solve`'s exact answer via the gradient-guided
        fast path, or raise :class:`GuidanceUnverified` (the caller
        falls back).  ``hint_model`` is the descent's rounded candidate
        (bool[n_vars]); None skips the verification gate and attempts
        the zero-backtrack walk directly (baseline-SAT problems need no
        hint at all)."""
        p = self.p
        if p.errors:
            raise InternalSolverError(p.errors)
        outcome, assign = self._test(guessed=())
        if outcome == UNSAT:
            raise GuidanceUnverified("baseline-unsat")
        if outcome == SAT:
            installed_idx = [i for i in range(self.n)
                             if assign[i] == _TRUE]
            return [p.variables[i] for i in installed_idx], installed_idx
        if hint_model is not None:
            hint = np.asarray(hint_model, dtype=bool)[: self.n]
            v_outcome, _ = self._test(
                guessed=(),
                extra_true=[int(i) for i in np.nonzero(hint)[0]],
                extra_false=[int(i) for i in np.nonzero(~hint)[0]],
            )
            if v_outcome != SAT:
                raise GuidanceUnverified("rounding-unverified")
        result, guessed_order, model = self._search_guided()
        if result != SAT or model is None:
            raise GuidanceUnverified("search-would-backtrack")
        return self._minimize(model, set(guessed_order))

    def _search_guided(self) -> Tuple[int, List[int], Optional[np.ndarray]]:
        """:meth:`_search` with the zero-backtrack discipline of
        :meth:`_search_warm` over the WHOLE problem: same deque walk,
        same Tests, but any UNSAT result aborts (via the UNSAT return —
        the caller raises) and the final completion runs
        :meth:`_dpll_guided`.  A walk that completes is, operation for
        operation, the canonical search's own no-backtrack trace."""
        p = self.p
        dq: _deque = _deque()
        for r in range(len(p.anchors)):
            dq.append((r, 0))
        guesses: List[_Guess] = []
        result = UNKNOWN
        model: Optional[np.ndarray] = None

        def assumed_vars() -> List[int]:
            return [g.var for g in guesses if g.var >= 0]

        while True:
            if not dq and result == UNKNOWN:
                model = self._dpll_guided(assumed_vars())
                result = SAT
            if result == UNSAT:
                return UNSAT, assumed_vars(), None
            if not dq:
                break
            cid, idx = dq.popleft()
            cands = [int(c) for c in p.choice_cand[cid] if c >= 0]
            var = cands[idx] if idx < len(cands) else -1
            assumed = set(assumed_vars())
            if any(c in assumed for c in cands):
                var = -1
            g = _Guess(choice=cid, index=idx, var=var, children=0)
            guesses.append(g)
            if var < 0:
                continue
            self._count_decision()
            for ch in p.var_choices[var] if var < len(p.var_choices) else []:
                if ch >= 0:
                    g.children += 1
                    dq.append((int(ch), 0))
            result, assign = self._test(guessed=assumed_vars())
            if result == SAT:
                model = assign
        return result, assumed_vars(), model

    def _dpll_guided(self, fixed_true: Sequence[int]) -> np.ndarray:
        """The completion DPLL of :meth:`_dpll`, restricted to the
        no-backtrack regime: lowest-index false-first decisions with the
        single in-place false→true flip canonical chronological
        backtracking performs on an immediate conflict.  Needing to pop
        a PREVIOUS decision voids the canonical-identity argument —
        raise and fall back."""
        assign = self._base.copy()
        assign[self.p.anchors] = _TRUE
        for m in fixed_true:
            assign[m] = _TRUE
        conflict, assign = self._bcp(assign)
        if conflict:
            raise GuidanceUnverified("completion-root-conflict")
        while True:
            self._count_step()
            unassigned = np.nonzero(assign[: self.n] == _UNASSIGNED)[0]
            if unassigned.size == 0:
                return assign
            var = int(unassigned[0])
            self._count_decision()
            trial = assign.copy()
            trial[var] = _FALSE
            conflict, trial = self._bcp(trial)
            if conflict:
                trial = assign.copy()
                trial[var] = _TRUE
                conflict, trial = self._bcp(trial)
                if conflict:
                    raise GuidanceUnverified("needs-backtrack")
            assign = trial

    # ----------------------------------------------------------- minimize

    def _minimize(
        self, model: np.ndarray, guessed: Set[int]
    ) -> Tuple[List[Variable], List[int]]:
        """Extras-only cardinality minimization (solve.go:86-113): variables
        chosen by the search stay installed, model-false variables stay out,
        and the count of incidental extras is driven to the minimum
        satisfiable w."""
        p = self.p
        extras = [
            i
            for i in range(self.n)
            if model[i] == _TRUE and i not in guessed
        ]
        excluded = [
            i
            for i in range(self.n)
            if model[i] != _TRUE and i not in guessed
        ]
        min_mask = np.zeros(self.n, dtype=bool)
        min_mask[extras] = True
        for w in range(len(extras) + 1):
            ok, m2 = self._dpll(
                fixed_true=sorted(guessed),
                fixed_false=excluded,
                min_mask=min_mask,
                min_w=w,
            )
            if ok:
                assert m2 is not None
                installed_idx = [i for i in range(self.n) if m2[i] == _TRUE]
                return [p.variables[i] for i in installed_idx], installed_idx
        raise InternalSolverError(["unexpected internal error: minimization failed"])

    # ------------------------------------------------- bounded solve (opt)

    def solve_bounded(
        self,
        obj_w: np.ndarray,
        obj_bound: int,
        seed_model: Optional[np.ndarray] = None,
        cone_mask: Optional[np.ndarray] = None,
    ) -> Tuple[bool, Optional[np.ndarray]]:
        """One bound-tightening probe for the optimize tier (ISSUE 18):
        find any model with ``sum(obj_w[v] for model-true v) <= obj_bound``,
        or prove none exists under the probe's scope.

        ``seed_model``/``cone_mask`` together form the warm (cone) variant
        mirroring the incremental tier's cone solve: off-cone vars are
        pinned to the seed model's phases and only the cone is re-searched.
        A warm probe's UNSAT is therefore NOT an optimality proof — the
        pinned prefix may be what blocks the bound — and callers must fall
        back to a cold (unseeded) probe before claiming one.  A cold
        probe's False return IS definitive: no model at this bound.

        Raises Incomplete/SolveCancelled through the step counter like
        every other entry point; ``p.errors`` raise InternalSolverError."""
        if self.p.errors:
            raise InternalSolverError(self.p.errors)
        fixed_true: List[int] = []
        fixed_false: List[int] = []
        if seed_model is not None and cone_mask is not None:
            for i in range(self.n):
                if cone_mask[i]:
                    continue
                if seed_model[i] == _TRUE:
                    fixed_true.append(i)
                else:
                    fixed_false.append(i)
        ok, model = self._dpll(
            fixed_true=fixed_true,
            fixed_false=fixed_false,
            obj_w=np.asarray(obj_w, dtype=np.int64)[: self.n],
            obj_bound=int(obj_bound),
        )
        return ok, model

    # ---------------------------------------------------------- unsat core

    def unsat_core_mask(self) -> np.ndarray:
        """Minimal unsat core as a boolean mask over applied-constraint
        indices, via deletion-based minimization: start from all
        constraints active and drop any whose removal keeps the remainder
        unsatisfiable, in constraint order.  Engine-agnostic analog of
        gini's failed-assumption ``Why`` (lit_mapping.go:198-207).

        Probes drop ONE constraint each, in constraint order — measured
        the right shape for this sweep: on an overconstrained catalog a
        single-drop probe dies to an immediate BCP conflict (~1 step),
        while any multi-drop segment/bisection probe leaves a weakly
        constrained remainder whose UNSAT proof needs real search (a
        hint-guided divide-and-conquer variant measured 3.5x SLOWER on the
        giant-catalog config despite ~25x fewer probes; don't re-try).
        Fast *exact* shortcut for giant problems: the driver's speculative
        parallel-probe path (engine.driver), which batches all single-drop
        probes as one device program and falls back to this loop when its
        one-probe verification fails.

        Public so the tensor driver can host-route core extraction for
        giant single problems (engine.driver.HOST_CORE_NCONS) with
        bit-identical results — this loop is the spec both the device's
        chunked deletion and the speculative path provably match."""
        p = self.p
        active = np.ones(p.n_cons, dtype=bool)
        for j in range(p.n_cons):
            if not active[j]:
                continue
            trial = active.copy()
            trial[j] = False
            ok, _ = self._dpll(anchors_assumed=False, act_enabled=trial)
            if not ok:
                active = trial
        return active

    def _unsat_core(self) -> List[AppliedConstraint]:
        """The mask above decoded to ``AppliedConstraint``s — what
        ``NotSatisfiable`` carries; yields the same (unique-minimal) cores
        the reference tests pin (solve_test.go:111-123,178-197,209-229)."""
        p = self.p
        if p.n_cons == 0:
            return []
        active = self.unsat_core_mask()
        return [p.applied[j] for j in range(p.n_cons) if active[j]]

    # ------------------------------------------------------------- budget

    def _count_step(self) -> None:
        self._steps += 1
        if self._cancel is not None and self._cancel.is_set():
            raise SolveCancelled()
        if self.max_steps is not None and self._steps > self.max_steps:
            raise Incomplete()

    def _count_decision(self) -> None:
        self.decisions += 1
        if self._hook_decision is not None:
            self._hook_decision()
