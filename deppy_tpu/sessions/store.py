"""The session store: retained interactive resolution state (ISSUE 20).

One :class:`SessionStore` per serving replica (constructed only when
``DEPPY_TPU_SESSIONS`` is on), holding :class:`Session` objects keyed
by a random id:

  * each session retains its **encoded problem + decode vocabulary**
    (a :class:`deppy_tpu.sat.Solver` with the request scheduler
    attached — the engine-registry-aware scope model) and a **private
    clause-set index** so consecutive solves warm-start from the
    session's own last model without ever touching the shared index;
  * the session's **family key** (the affinity key over its ordered
    variable ids) is returned at creation and echoed by clients in the
    ``X-Deppy-Session`` header, so the fleet router routes every op of
    a session to the replica holding it without re-encoding anything;
  * a **lease** (renewed by every op) bounds retention; a jittered
    sweeper expires lapsed sessions in the background and every
    map-touching path expires them lazily;
  * **caps** bound memory: a global hard cap and a per-tenant cap.
    At a cap, expired sessions are LRU-evicted first; if none remain
    the creation **sheds** (a counted 503/Retry-After, exactly like
    the fair-admission gate) rather than evicting live state.

Ops answer byte-identically to the equivalent one-shot cold resolve:
an assumption is materialized as a real constraint (``Mandatory`` /
``Prohibited``) on its subject variable, so the solved problem IS the
problem a fresh ``/v1/resolve`` of the derived document would solve —
same fingerprints, same unsat-core strings, same minimization.
"""

from __future__ import annotations

import secrets
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from .. import config, faults, telemetry
from .. import io as problem_io
from ..fleet.ring import affinity_key
from ..fleet.snapshot import import_index_entry, index_entry_to_dict
from ..incremental import ClauseSetIndex
from ..sat.errors import InternalSolverError
from ..sat.solver import Solver

# A session's private warm index only ever needs the latest few models
# (the current assumption state and its immediate neighborhood); a tiny
# capacity keeps per-session memory bounded at catalog size, not
# history size.
SESSION_INDEX_CAPACITY = 4

_OPS = ("assume", "test", "untest", "resolve", "explain")


class SessionError(ValueError):
    """Malformed session op (rendered as a 400)."""


class SessionLost(KeyError):
    """Unknown/expired session id (rendered as a 404; the router turns
    a retried 404 into the 409 "session lost" contract)."""


class SessionShed(RuntimeError):
    """Creation shed at a session cap (rendered as a 503)."""

    def __init__(self, scope: str):
        super().__init__(f"session cap reached ({scope})")
        self.scope = scope


class Session:
    """One retained interactive resolution session."""

    __slots__ = ("id", "tenant", "key", "solver", "index", "deadline",
                 "ops", "created", "lock")

    def __init__(self, sid: str, tenant: str, solver: Solver,
                 index: ClauseSetIndex, lease_s: float):
        from ..analysis import lockdep

        self.id = sid
        self.tenant = tenant
        # The family key over the ORDERED variable identifiers — the
        # affinity-ring key the router routes ops by (X-Deppy-Session).
        self.key = affinity_key(
            v.identifier for v in solver.problem.variables)
        self.solver = solver
        self.index = index
        self.deadline = time.monotonic() + lease_s
        self.ops = 0
        self.created = time.time()
        # Ops on ONE session serialize (the scope stack is stateful);
        # distinct sessions run concurrently.  Never held across a
        # store-lock acquisition (store -> session is the only nesting
        # order, and only for bookkeeping, never across a solve).
        self.lock = lockdep.make_lock("sessions.session")

    def expired(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.monotonic()) \
            >= self.deadline

    def renew(self, lease_s: float) -> None:
        self.deadline = time.monotonic() + lease_s


class SessionStore:
    """Create/drive/expire sessions; export/import them for handoff."""

    def __init__(self, scheduler, metrics=None,
                 lease_s: Optional[float] = None,
                 max_sessions: Optional[int] = None,
                 max_per_tenant: Optional[int] = None,
                 replica: Optional[str] = None,
                 sweep_interval_s: Optional[float] = None):
        from ..analysis import lockdep

        self.scheduler = scheduler
        self.replica = replica
        if lease_s is None:
            lease_s = config.env_float("DEPPY_TPU_SESSION_LEASE_S", 300.0,
                                       strict=False)
        self.lease_s = max(float(lease_s), 0.05)
        if max_sessions is None:
            max_sessions = config.env_int("DEPPY_TPU_SESSION_MAX", 256,
                                          strict=False)
        self.max_sessions = max(int(max_sessions), 1)
        if max_per_tenant is None:
            max_per_tenant = config.env_int(
                "DEPPY_TPU_SESSION_MAX_PER_TENANT", 64, strict=False)
        self.max_per_tenant = max(int(max_per_tenant), 1)
        self._registry = metrics if metrics is not None \
            else telemetry.default_registry()
        # Guards the id map and per-tenant counts only — never held
        # across a solve (a slow op must not serialize every other
        # session's bookkeeping).
        self._lock = lockdep.make_lock("sessions.store")
        self._sessions: "OrderedDict[str, Session]" = OrderedDict()
        self._tenants: Dict[str, int] = {}
        # The ISSUE 20 metric families — registered here, so with the
        # tier off (no store constructed) none of them exists.
        reg = self._registry
        self._g_active = reg.gauge(
            "deppy_session_active",
            "Live resolution sessions held by this replica.")
        self._g_active.set(0)
        self._c_ops = reg.counter(
            "deppy_session_ops_total",
            "Session ops served, by op.", labelname="op").preset(*_OPS)
        self._c_expired = reg.counter(
            "deppy_session_expired_total",
            "Sessions expired by lease (sweeper or lazy).")
        self._c_evictions = reg.counter(
            "deppy_session_evictions_total",
            "Sessions evicted or creations shed at a cap, by reason.",
            labelname="reason").preset("cap_expired", "shed")
        # Jittered sweeper (the lease renew-jitter idiom): replicas
        # started together must not sweep in lockstep forever.
        self._sweep_s = sweep_interval_s if sweep_interval_s is not None \
            else min(max(self.lease_s / 4.0, 0.05), 30.0)
        self._stop = threading.Event()
        self._sweeper = threading.Thread(
            target=self._sweep_loop, daemon=True,
            name="deppy-session-sweeper")
        self._sweeper.start()

    # ------------------------------------------------------------ lifecycle

    def stop(self) -> None:
        self._stop.set()
        self._sweeper.join(timeout=5)

    def _sweep_loop(self) -> None:
        import random

        while not self._stop.is_set():
            self._stop.wait(self._sweep_s * (1.0 + 0.2 * random.random()))
            if self._stop.is_set():
                return
            self.sweep()

    def sweep(self) -> int:
        """Expire every lapsed session; returns the count (exposed for
        tests and called by the background sweeper)."""
        now = time.monotonic()
        with self._lock:
            lapsed = [sid for sid, s in self._sessions.items()
                      if s.expired(now)]
            for sid in lapsed:
                self._drop_locked(sid)
            if lapsed:
                self._c_expired.inc(len(lapsed))
        return len(lapsed)

    def _drop_locked(self, sid: str) -> Optional[Session]:
        s = self._sessions.pop(sid, None)
        if s is None:
            return None
        n = self._tenants.get(s.tenant, 0) - 1
        if n > 0:
            self._tenants[s.tenant] = n
        else:
            self._tenants.pop(s.tenant, None)
        self._g_active.set(len(self._sessions))
        return s

    # --------------------------------------------------------------- create

    def _evict_expired_locked(self, tenant: Optional[str] = None) -> bool:
        """LRU-evict ONE expired session (of ``tenant`` when given);
        True when a slot was freed.  Live sessions are never evicted —
        the cap sheds instead."""
        now = time.monotonic()
        for sid, s in self._sessions.items():  # OrderedDict = LRU order
            if s.expired(now) and (tenant is None or s.tenant == tenant):
                self._drop_locked(sid)
                self._c_expired.inc()
                self._c_evictions.inc(label="cap_expired")
                return True
        return False

    def create(self, doc, tenant: str = "default") -> dict:
        """Create a session from a single-problem document
        (``{"variables": [...]}``); returns the creation envelope
        (``id``, the family ``key`` clients echo as X-Deppy-Session,
        and the lease).  Raises :class:`ProblemFormatError` /
        :class:`InternalSolverError` for malformed catalogs (400) and
        :class:`SessionShed` at a cap (503)."""
        variables = problem_io.problem_from_dict(doc)
        solver = Solver(variables, scheduler=self.scheduler,
                        tenant=tenant)
        if solver.problem.errors:
            raise InternalSolverError(solver.problem.errors)
        index = ClauseSetIndex(capacity=SESSION_INDEX_CAPACITY,
                               registry=self._registry)
        solver.warm_index = index
        sid = secrets.token_hex(12)
        with self._lock:
            if self._tenants.get(tenant, 0) >= self.max_per_tenant:
                if not self._evict_expired_locked(tenant):
                    self._c_evictions.inc(label="shed")
                    raise SessionShed("tenant")
            if len(self._sessions) >= self.max_sessions:
                if not self._evict_expired_locked():
                    self._c_evictions.inc(label="shed")
                    raise SessionShed("global")
            s = Session(sid, tenant, solver, index, self.lease_s)
            self._sessions[sid] = s
            self._tenants[tenant] = self._tenants.get(tenant, 0) + 1
            self._g_active.set(len(self._sessions))
        return {"id": sid, "key": s.key, "lease_s": self.lease_s,
                "n_vars": len(solver.problem.variables)}

    # ------------------------------------------------------------------ ops

    def _get(self, sid: str) -> Session:
        with self._lock:
            s = self._sessions.get(sid)
            if s is not None and s.expired():
                self._drop_locked(sid)
                self._c_expired.inc()
                s = None
            if s is None:
                raise SessionLost(sid)
            self._sessions.move_to_end(sid)  # LRU touch
            return s

    def op(self, sid: str, doc, deadline_s: Optional[float] = None) -> dict:
        """Drive one op against the retained state.  ``doc`` is
        ``{"op": "assume"|"test"|"untest"|"resolve"|"explain", ...}``;
        solve-carrying ops answer byte-identically to a one-shot cold
        resolve of the derived document.  Raises :class:`SessionLost`
        (404/409), :class:`SessionError` (400), and whatever the solve
        path raises (500 contract unchanged)."""
        faults.inject("sessions.op")
        if not isinstance(doc, dict) or doc.get("op") not in _OPS:
            raise SessionError(
                f'"op" must be one of {", ".join(_OPS)}')
        s = self._get(sid)
        op = doc["op"]
        attrs = {"op": op, "session": sid, "tenant": s.tenant}
        if self.replica is not None:
            attrs["replica"] = self.replica
        with telemetry.default_registry().span("session.op", **attrs):
            with s.lock:
                s.renew(self.lease_s)
                s.ops += 1
                out = self._op_inner(s, op, doc, deadline_s)
        self._c_ops.inc(label=op)
        return out

    def _op_inner(self, s: Session, op: str, doc: dict,
                  deadline_s: Optional[float]) -> dict:
        if op == "assume":
            idents = doc.get("identifiers")
            if not isinstance(idents, list) or not idents \
                    or not all(isinstance(i, str) for i in idents):
                raise SessionError(
                    '"identifiers" must be a non-empty list of strings')
            installed = doc.get("installed", True)
            if not isinstance(installed, bool):
                raise SessionError('"installed" must be a boolean')
            try:
                s.solver.assume(*idents, installed=installed)
            except InternalSolverError as e:
                raise SessionError("; ".join(e.errors)) from e
            return {"op": "assume",
                    "assumed": len(s.solver.assumptions())}
        if op == "test":
            # Propagation-only scope probe (gini Test): host-cheap by
            # design, so it stays on the inline spec engine like the
            # library facade.
            verdict = s.solver.test()
            return {"op": "test", "result": verdict,
                    "depth": s.solver.scope_depth()}
        if op == "untest":
            try:
                depth = s.solver.untest()
            except InternalSolverError as e:
                raise SessionError("; ".join(e.errors)) from e
            return {"op": "untest", "depth": depth}
        # resolve / explain: the full solve, routed engine-registry-
        # aware through the scheduler's session class.  The rendered
        # "result" object is byte-identical to the corresponding entry
        # of a one-shot /v1/resolve of the derived document.
        stats: dict = {}
        r = s.solver.solve_scoped(deadline_s=deadline_s, stats=stats)
        out = {"op": op, "result": problem_io.result_to_dict(r)}
        if stats.get("warm"):
            out["warm"] = True
        return out

    # ------------------------------------------------------ handoff codec

    def export_entries(self) -> List[dict]:
        """Serialize every live session for the drain/join snapshot
        stream.  Lease deadlines export as REMAINING seconds (monotonic
        clocks do not travel between replicas); the private warm index
        rides along in the exact checksummed entry format the shared
        index uses."""
        with self._lock:
            sessions = list(self._sessions.values())
        now = time.monotonic()
        out = []
        for s in sessions:
            with s.lock:
                if s.expired(now):
                    continue
                assumptions, scopes, scope_base = s.solver.scope_state()
                out.append({
                    "id": s.id,
                    "tenant": s.tenant,
                    "affinity": s.key,
                    "variables": [problem_io.variable_to_dict(v)
                                  for v in s.solver.problem.variables],
                    "assumptions": [[i, bool(b)] for i, b in assumptions],
                    "scopes": list(scopes),
                    "scope_base": scope_base,
                    "lease_remaining_s": max(s.deadline - now, 0.0),
                    "ops": s.ops,
                    "index": [index_entry_to_dict(e)
                              for e in s.index.export_entries()],
                })
        return out

    def import_entry(self, raw) -> bool:
        """Rebuild one exported session (join/drain inheritance).
        Live-wins by id; a malformed entry is skipped (False), never
        fatal — exactly the index-entry import posture."""
        try:
            sid = str(raw["id"])
            tenant = str(raw["tenant"])
            variables = [problem_io.variable_from_dict(d)
                         for d in raw["variables"]]
            assumptions = [(str(i), bool(b))
                           for i, b in raw["assumptions"]]
            scopes = [int(x) for x in raw.get("scopes", [])]
            scope_base = int(raw.get("scope_base", 0))
            lease_remaining = float(raw.get("lease_remaining_s", 0.0))
        except (KeyError, TypeError, ValueError):
            return False
        if lease_remaining <= 0.0:
            return False
        solver = Solver(variables, scheduler=self.scheduler,
                        tenant=tenant)
        if solver.problem.errors:
            return False
        index = ClauseSetIndex(capacity=SESSION_INDEX_CAPACITY,
                               registry=self._registry)
        solver.warm_index = index
        try:
            self._replay_scope(solver, assumptions, scopes, scope_base)
        except (InternalSolverError, IndexError, ValueError):
            return False
        for entry in raw.get("index") or []:
            try:
                import_index_entry(index, entry)
            # deppy: lint-ok[exception-hygiene] a poisoned private-index entry only costs warmth, never the session
            except Exception:
                continue
        s = Session(sid, tenant, solver, index,
                    min(lease_remaining, self.lease_s))
        s.ops = int(raw.get("ops", 0))
        with self._lock:
            if sid in self._sessions:
                return False  # live state wins
            if len(self._sessions) >= self.max_sessions \
                    or self._tenants.get(tenant, 0) >= self.max_per_tenant:
                if not self._evict_expired_locked():
                    self._c_evictions.inc(label="shed")
                    return False
            self._sessions[sid] = s
            self._tenants[tenant] = self._tenants.get(tenant, 0) + 1
            self._g_active.set(len(self._sessions))
        return True

    @staticmethod
    def _replay_scope(solver: Solver, assumptions: List[tuple],
                      scopes: List[int], scope_base: int) -> None:
        """Reconstruct the engine's scope stack through the public
        assume/test surface.  ``test()`` pushes the previous base and
        records the assumed-length at each push, so the lengths at
        historical test() calls are ``scopes[1:] + [scope_base]``."""
        lens = (scopes[1:] + [scope_base]) if scopes else []
        idx = 0
        for ln in lens:
            if ln < idx or ln > len(assumptions):
                raise ValueError("inconsistent scope stack")
            for ident, installed in assumptions[idx:ln]:
                solver.assume(ident, installed=installed)
            idx = ln
            solver.test()
        if scope_base > len(assumptions) and not scopes:
            raise ValueError("inconsistent scope stack")
        for ident, installed in assumptions[idx:]:
            solver.assume(ident, installed=installed)

    # ------------------------------------------------------------ accounting

    def active(self) -> int:
        with self._lock:
            return len(self._sessions)

    def stats(self) -> dict:
        with self._lock:
            return {"active": len(self._sessions),
                    "tenants": dict(self._tenants),
                    "lease_s": self.lease_s,
                    "max_sessions": self.max_sessions,
                    "max_per_tenant": self.max_per_tenant}
