"""Stateful resolution sessions (ISSUE 20).

The interactive twin of the stateless ``/v1/resolve`` path: a client
creates a session pinned to a catalog epoch (the encoded problem and
its decode vocabulary retained server-side under a lease), then drives
gini-style ``assume`` / ``test`` / ``untest`` / ``resolve`` /
``explain`` ops against the retained state instead of re-sending the
whole catalog per question.  Every incremental solve routes through
the request scheduler's dedicated session class — warm-started from
the session's own last model, raced across registry backends, subject
to deadlines/breaker/fair admission unchanged — and answers
byte-identically to the equivalent one-shot cold resolve.

Sessions are warm state like everything else in the fleet: keyed by
family so the affinity ring routes every op to the replica holding
them, exported/imported in the drain/join snapshot stream, expired by
lease with a sweeper, and bounded per tenant.
"""

from .store import Session, SessionStore

__all__ = ["Session", "SessionStore"]
