"""Optimize-request parsing and objective construction (ISSUE 18).

The optimization tier answers *best* solutions, and every query class
reduces to one shape: a linear objective over the problem variables,
minimized by the bound-tightening loop in :mod:`.loop`.  This module is
the format layer — it turns the wire document into variables plus an
:class:`Objective` in SIGNED form:

    cost(model) = offset + sum(signed[v] for model-true v)

where ``signed[v] = cost_true[v] - cost_false[v]`` and ``offset`` is the
sum of the cost-when-false terms.  Folding to signed form is what lets
one engine-side bound (``HostEngine.solve_bounded``) serve all three
query classes: a "keep this installed" preference is a cost WHEN FALSE,
which becomes a negative signed weight, not a second constraint kind.

Query classes:

* ``upgrade`` — minimal-change upgrade planning: "newest acceptable
  bundles, fewest installed entities touched".  Lexicographic via big-M:
  each missed ``prefer`` id costs BIG = n_vars + 1, each touch (an
  installed id removed, a non-installed id added) costs 1.  BIG strictly
  dominates the touch level (at most n touches exist), so one combined
  objective preserves the two-level order inside ONE tightening loop.
* ``soft`` — MaxSAT-style weighted preferences: each violated soft
  constraint costs its weight (positive integer, capped by the
  ``DEPPY_TPU_OPT_MAX_WEIGHT`` knob).
* ``explain`` — no objective at all: the named goals become mandatory
  and the answer is either a plan or the unsat core as a blocking set.

An all-{0,1}-signed objective ("unit-positive") additionally lowers
NATIVELY: the bound "at most W of the weighted vars true" is exactly an
``AtMost`` row carried by a synthetic variable, which makes the probe a
plain :class:`Problem` every registry backend can race.  Mixed-sign or
weighted objectives stay on the host objective engine (the one
``bound_weights`` backend) — see ``registry.optimize_candidates``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..sat.constraints import Variable, at_most, mandatory, variable
from ..sat.encode import Problem

QUERIES = ("upgrade", "soft", "explain")

# Carrier for the native AtMost lowering.  Dunder-fenced so a real
# catalog id never collides by accident; a catalog that DOES use the
# name simply loses the native route (the loop falls back to the host
# objective engine), never correctness.
BOUND_VARIABLE_ID = "__deppy_optimize_bound__"


class OptimizeFormatError(ValueError):
    """Raised on a malformed optimize request document (a 400, like
    ``PublishFormatError`` on the publish endpoint)."""


class Objective:
    """A linear objective in signed form over ``n`` problem variables.

    ``signed`` is int64[n]; ``offset`` re-bases values so
    :meth:`value` reports the human cost (0 = every preference met).
    ``floor`` is the least value ANY assignment can take — reaching it
    proves optimality without an UNSAT probe."""

    __slots__ = ("signed", "offset")

    def __init__(self, signed: np.ndarray, offset: int):
        self.signed = np.asarray(signed, dtype=np.int64)
        self.offset = int(offset)

    @property
    def floor(self) -> int:
        return self.offset + int(self.signed[self.signed < 0].sum())

    def value(self, model_true: np.ndarray) -> int:
        """Objective of one model, from its boolean installed mask."""
        return self.offset + int(self.signed[model_true].sum())

    def bound_for(self, value: int) -> int:
        """The engine-side ``obj_bound`` for "cost <= value"."""
        return int(value) - self.offset

    @property
    def unit_positive(self) -> bool:
        """Whether the objective lowers natively to one AtMost row."""
        return self.offset == 0 and bool(
            ((self.signed == 0) | (self.signed == 1)).all())

    def bearing_mask(self, model_true: np.ndarray) -> np.ndarray:
        """Vars where THIS model pays: true with positive weight, or
        false with negative weight — the warm probe's cone seed (any
        cheaper model must flip at least one of these)."""
        return ((model_true & (self.signed > 0))
                | (~model_true & (self.signed < 0)))


class OptimizeRequest:
    """One parsed optimize request: catalog variables + query fields."""

    __slots__ = ("variables", "query", "installed", "prefer", "soft",
                 "goal", "warm")

    def __init__(self, variables: Tuple[Variable, ...], query: str,
                 installed: Tuple[str, ...], prefer: Tuple[str, ...],
                 soft: Tuple[dict, ...], goal: Tuple[str, ...],
                 warm: bool):
        self.variables = variables
        self.query = query
        self.installed = installed
        self.prefer = prefer
        self.soft = soft
        self.goal = goal
        self.warm = warm

    @classmethod
    def from_doc(cls, doc, max_weight: int) -> "OptimizeRequest":
        from .. import io as problem_io

        if not isinstance(doc, dict):
            raise OptimizeFormatError(
                f"optimize body must be an object, got {type(doc).__name__}")
        raw_vars = doc.get("variables")
        if not isinstance(raw_vars, list) or not raw_vars:
            raise OptimizeFormatError(
                '"variables" must be a non-empty list')
        try:
            variables = tuple(problem_io.variable_from_dict(v)
                              for v in raw_vars)
        except problem_io.ProblemFormatError as e:
            raise OptimizeFormatError(str(e)) from e
        query = doc.get("query")
        if query not in QUERIES:
            raise OptimizeFormatError(
                f'"query" must be one of {list(QUERIES)}, got {query!r}')
        known = {str(v.identifier) for v in variables}

        def ids(field: str, require_known: bool) -> Tuple[str, ...]:
            raw = doc.get(field, [])
            if not isinstance(raw, list) \
                    or not all(isinstance(i, str) for i in raw):
                raise OptimizeFormatError(
                    f'"{field}" must be a list of ids')
            if require_known:
                for i in raw:
                    if i not in known:
                        raise OptimizeFormatError(
                            f'"{field}" names unknown id {i!r}')
            return tuple(raw)

        # Installed ids absent from the catalog are IGNORED, not errors:
        # a withdrawn bundle is the normal reason to plan an upgrade.
        installed = tuple(i for i in ids("installed", False) if i in known)
        prefer = ids("prefer", True)
        goal = ids("goal", True)
        soft_raw = doc.get("soft", [])
        if not isinstance(soft_raw, list):
            raise OptimizeFormatError('"soft" must be a list')
        soft: List[dict] = []
        for entry in soft_raw:
            if not isinstance(entry, dict) \
                    or not isinstance(entry.get("id"), str):
                raise OptimizeFormatError(
                    'each soft constraint requires a string "id"')
            if entry["id"] not in known:
                raise OptimizeFormatError(
                    f'"soft" names unknown id {entry["id"]!r}')
            w = entry.get("weight", 1)
            if not isinstance(w, int) or isinstance(w, bool) or w < 1:
                raise OptimizeFormatError(
                    f'soft weight for {entry["id"]!r} must be a '
                    f'positive integer, got {w!r}')
            if w > max_weight:
                raise OptimizeFormatError(
                    f'soft weight for {entry["id"]!r} exceeds the '
                    f'configured cap ({w} > {max_weight})')
            installed_pref = entry.get("installed", True)
            if not isinstance(installed_pref, bool):
                raise OptimizeFormatError(
                    f'soft "installed" for {entry["id"]!r} must be a '
                    'boolean')
            soft.append({"id": entry["id"], "installed": installed_pref,
                         "weight": w})
        if query == "soft" and not soft:
            raise OptimizeFormatError(
                'query "soft" requires a non-empty "soft" list')
        if query == "explain" and not goal:
            raise OptimizeFormatError(
                'query "explain" requires a non-empty "goal" list')
        warm = doc.get("warm", True)
        if not isinstance(warm, bool):
            raise OptimizeFormatError('"warm" must be a boolean')
        return cls(variables, query, installed, prefer, tuple(soft),
                   goal, warm)


def build_objective(req: OptimizeRequest,
                    index: Dict[str, int], n: int) -> Objective:
    """The request's objective in signed form (upgrade/soft queries)."""
    signed = np.zeros(n, dtype=np.int64)
    offset = 0
    if req.query == "upgrade":
        big = n + 1
        installed = set(req.installed)
        for pid in req.prefer:
            signed[index[pid]] -= big
            offset += big
        for i in range(n):
            # Level 2, the touch count: removing an installed entity
            # and adding a non-installed one each cost 1.
            vid = str(req.variables[i].identifier)
            if vid in installed:
                signed[i] -= 1
                offset += 1
            else:
                signed[i] += 1
    else:
        for entry in req.soft:
            i = index[entry["id"]]
            if entry["installed"]:
                signed[i] -= entry["weight"]
                offset += entry["weight"]
            else:
                signed[i] += entry["weight"]
    return Objective(signed, offset)


def explain_variables(req: OptimizeRequest) -> Tuple[Variable, ...]:
    """The catalog with every goal id made mandatory — feasibility of
    this family IS the explain question, and its unsat core IS the
    blocking set."""
    goals = set(req.goal)
    out: List[Variable] = []
    for v in req.variables:
        if str(v.identifier) in goals:
            out.append(Variable(v.identifier,
                                tuple(v.constraints) + (mandatory(),)))
        else:
            out.append(v)
    return tuple(out)


def native_bound_variables(
        variables: Sequence[Variable], objective: Objective,
        bound: int) -> Optional[Tuple[Variable, ...]]:
    """The probe family for the native AtMost lowering, or None when
    the objective (or an id collision) disqualifies it.

    A unit-positive objective's bound "cost <= W" is exactly "at most W
    of the weight-1 vars true" — one AtMost row on a synthetic carrier
    variable.  Activation vars are always assumed TRUE, so the row
    applies unconditionally; the carrier itself is otherwise free and
    is stripped from the answer by the loop."""
    if not objective.unit_positive or bound < 0:
        return None
    if any(str(v.identifier) == BOUND_VARIABLE_ID for v in variables):
        return None
    members = [str(variables[i].identifier)
               for i in np.nonzero(objective.signed == 1)[0]]
    carrier = variable(BOUND_VARIABLE_ID, at_most(int(bound), *members))
    return tuple(variables) + (carrier,)


def cone_mask(problem: Problem, model_true: np.ndarray,
              objective: Objective, hops: int = 2) -> np.ndarray:
    """The warm probe's cone: the previous model's cost-bearing vars
    expanded ``hops`` times through shared clause/cardinality rows —
    the same shape as the incremental tier's delta cone, seeded by
    objective incidence instead of changed constraints.  Off-cone vars
    get pinned to the seed model's phases, so a warm probe only
    re-searches where an improvement can actually come from."""
    n = problem.n_vars
    mask = objective.bearing_mask(model_true).copy()
    cls = problem.clauses
    cls_var = np.abs(cls) - 1           # -1 on pads
    cls_ok = (cls != 0) & (cls_var >= 0) & (cls_var < n)
    card_var = problem.card_ids
    card_ok = (card_var >= 0) & (card_var < n)
    for _ in range(max(int(hops), 0)):
        grown = mask.copy()
        if cls_var.size:
            hit = (cls_ok & mask[np.where(cls_ok, cls_var, 0)]).any(axis=1)
            touched = cls_var[hit][cls_ok[hit]]
            grown[touched] = True
        if card_var.size:
            hit = (card_ok & mask[np.where(card_ok, card_var, 0)]).any(axis=1)
            touched = card_var[hit][card_ok[hit]]
            grown[touched] = True
        if (grown == mask).all():
            break
        mask = grown
    return mask
