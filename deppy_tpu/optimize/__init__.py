"""Optimization tier (ISSUE 18): best-solution queries above plain
resolution — minimal-change upgrade planning, weighted soft
constraints, and explain-why-not blocking sets, all served by one
bound-tightening loop that rides the scheduler's idle-priority queue.

Surface: :class:`Planner` (the serving core the service constructs
behind ``POST /v1/optimize``), :class:`OptimizeRequest` /
:class:`Objective` (the format layer), and
:class:`OptimizeFormatError` (the endpoint's 400)."""

from .loop import Planner
from .objective import (
    BOUND_VARIABLE_ID,
    Objective,
    OptimizeFormatError,
    OptimizeRequest,
    build_objective,
    cone_mask,
    explain_variables,
    native_bound_variables,
)

__all__ = [
    "BOUND_VARIABLE_ID",
    "Objective",
    "OptimizeFormatError",
    "OptimizeRequest",
    "Planner",
    "build_objective",
    "cone_mask",
    "explain_variables",
    "native_bound_variables",
]
