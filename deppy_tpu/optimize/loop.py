"""Bound-tightening optimization loop (ISSUE 18 tentpole).

One loop serves every optimize query class: solve for ANY model, then
repeatedly probe "is there a model with cost <= best - 1?" until a
probe's UNSAT proves optimality, the objective floor is reached, or a
budget degrades the request to best-so-far.  The loop is a first-class
serving citizen, not a library spin:

* every probe rides :meth:`Scheduler.submit_optimize` — the idle
  (speculative-class) queue — when it lowers natively, so a long
  optimization coalesces at flush boundaries like churn and live
  resolution traffic preempts every iteration;
* native (unit-positive) probes are plain :class:`Problem`\\ s the
  portfolio racer dispatches across the registry's definitive
  backends; mixed-sign probes pin to the host objective engine, the
  registry's one ``bound_weights`` backend
  (``registry.optimize_candidates`` makes that routing data-driven);
* warm probes re-search only the objective cone of the previous model
  (PR 9's cone-solve shape), which is where the warm-vs-cold iteration
  rate the upgrade bench pins comes from — a warm probe's UNSAT is
  never a proof, the cold fallback's is;
* every probe emits an ``optimize.iteration`` span plus a sink
  ``optimize`` event, and the tier's counters
  (``deppy_optimize_{iterations,improvements,proofs}_total``) land on
  the serving registry the scrape endpoint renders.

Answer canonicality: the loop's last act is a CANONICAL cold bounded
solve at the proven best cost.  Every model at that bound has exactly
the optimal cost, and the host DPLL's false-first, lowest-index order
returns the lexicographically least of them — the tie-break the
fuzz-differential oracle in tests/test_optimize.py enumerates.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from .. import config, telemetry
from ..engine import registry as engine_registry
from ..sat.constraints import Variable
from ..sat.encode import encode
from ..sat.errors import Incomplete, InternalSolverError, NotSatisfiable
from ..sat.host import HostEngine
from .objective import (
    Objective,
    OptimizeRequest,
    build_objective,
    cone_mask,
    explain_variables,
    native_bound_variables,
)

DEFAULT_MAX_ITERATIONS = 64
DEFAULT_ITER_BUDGET = 1 << 20
DEFAULT_MAX_WEIGHT = 64

# A warm cone covering more than this fraction of the problem is no
# cone at all — the probe would re-search nearly everything while
# dragging the pinned prefix's bias; go straight to the cold probe
# (the incremental tier draws the same line for delta cones).
MAX_CONE_FRACTION = 0.5


class Planner:
    """The optimization tier's serving core: parse → objective →
    tightening loop → canonical answer.

    Constructed by the service when ``DEPPY_TPU_OPT`` is on (counters
    land on the server's scrape registry) or directly in library use.
    ``handle`` raises :class:`OptimizeFormatError` for malformed
    documents (the service's 400) and ``InternalSolverError`` for
    unresolvable references, mirroring the resolve path's screening."""

    def __init__(self, scheduler, metrics=None,
                 max_iterations: Optional[int] = None,
                 iter_budget: Optional[int] = None,
                 max_weight: Optional[int] = None):
        self.scheduler = scheduler
        self.max_iterations = (
            max_iterations if max_iterations is not None
            else config.env_int("DEPPY_TPU_OPT_MAX_ITERATIONS",
                                DEFAULT_MAX_ITERATIONS, strict=False))
        self.iter_budget = (
            iter_budget if iter_budget is not None
            else config.env_int("DEPPY_TPU_OPT_ITER_BUDGET",
                                DEFAULT_ITER_BUDGET, strict=False))
        self.max_weight = (
            max_weight if max_weight is not None
            else config.env_int("DEPPY_TPU_OPT_MAX_WEIGHT",
                                DEFAULT_MAX_WEIGHT, strict=False))
        reg = metrics if metrics is not None \
            else telemetry.default_registry()
        self._c_iterations = reg.counter(
            "deppy_optimize_iterations_total",
            "Bound-tightening probes run, by mode (warm = cone probe "
            "seeded from the previous model, cold = complete probe).",
            labelname="mode")
        self._c_improvements = reg.counter(
            "deppy_optimize_improvements_total",
            "Probes that found a strictly better model.")
        self._c_proofs = reg.counter(
            "deppy_optimize_proofs_total",
            "Optimality proofs, by kind (unsat_probe = a cold probe "
            "below the best cost proved UNSAT; floor = the objective's "
            "lower bound was reached).", labelname="kind")

    # ------------------------------------------------------------- entry

    def handle(self, doc, deadline_s: Optional[float] = None,
               tenant: str = "default") -> dict:
        """Serve one optimize request document; returns the response
        payload (the service wraps it as ``{"optimize": ...}``)."""
        req = OptimizeRequest.from_doc(doc, self.max_weight)
        if req.query == "explain":
            return self._explain(req, deadline_s, tenant)
        return self._tighten(req, deadline_s, tenant)

    # ----------------------------------------------------------- explain

    def _explain(self, req: OptimizeRequest,
                 deadline_s: Optional[float], tenant: str) -> dict:
        """Explain-why-not: the goals become mandatory, and the family's
        unsat core — extracted by whatever definitive backend answered —
        IS the human-readable blocking set."""
        family = list(explain_variables(req))
        res = self.scheduler.submit_optimize(
            [family], deadline_s=deadline_s, tenant=tenant)[0]
        out: dict = {"query": "explain", "goal": list(req.goal)}
        if isinstance(res, dict):
            out["status"] = "feasible"
            out["plan"] = sorted(str(k) for k, v in res.items() if v)
        elif isinstance(res, NotSatisfiable):
            out["status"] = "blocked"
            out["blocking"] = [str(c) for c in res.constraints]
        else:
            out["status"] = "degraded"
            out["reason"] = "feasibility-budget"
        return out

    # ---------------------------------------------------------- tighten

    def _tighten(self, req: OptimizeRequest,
                 deadline_s: Optional[float], tenant: str) -> dict:
        variables = list(req.variables)
        p = encode(variables)
        if p.errors:
            raise InternalSolverError(p.errors)
        n = p.n_vars
        index = {str(v.identifier): i for i, v in enumerate(variables)}
        objective = build_objective(req, index, n)
        deadline_t = (time.monotonic() + deadline_s
                      if deadline_s is not None else None)
        reg = telemetry.default_registry()

        out: dict = {"query": req.query, "iterations": 0,
                     "improvements": 0, "optimal": False, "proof": None}

        res = self._submit(variables, deadline_t, tenant, None)
        if isinstance(res, NotSatisfiable):
            # Infeasible outright: explain-why-not for free.
            out["status"] = "unsat"
            out["blocking"] = [str(c) for c in res.constraints]
            return out
        if not isinstance(res, dict):
            out["status"] = "degraded"
            out["reason"] = "feasibility-budget"
            return out
        best = np.fromiter((bool(res[v.identifier]) for v in variables),
                           dtype=bool, count=n)
        cost = objective.value(best)
        floor = objective.floor
        iterations = 0
        improvements = 0
        proof: Optional[str] = None
        reason: Optional[str] = None
        try_warm = req.warm

        if cost <= floor:
            proof = "floor"
            self._c_proofs.inc(label="floor")
        while proof is None and reason is None:
            if iterations >= self.max_iterations:
                reason = "iteration-cap"
                break
            if deadline_t is not None \
                    and time.monotonic() >= deadline_t:
                reason = "deadline"
                break
            bound = cost - 1
            iterations += 1
            mode = "warm" if try_warm else "cold"
            if mode == "warm":
                cone = cone_mask(p, best, objective)
                if int(cone.sum()) > MAX_CONE_FRACTION * n:
                    mode = "cold"
            self._c_iterations.inc(label=mode)
            t0 = time.perf_counter()
            backend = "host"
            outcome = "unsat"
            delta = 0
            model: Optional[np.ndarray] = None
            with reg.span("optimize.iteration", iteration=iterations,
                          bound=bound, mode=mode, tenant=tenant) as sp:
                if mode == "warm":
                    status, m = self._host_probe(p, objective, bound,
                                                 seed=best, cone=cone)
                    if status == "sat":
                        model = m
                    else:
                        # A warm UNSAT/budget miss is NOT a proof — the
                        # pinned off-cone prefix may be what blocks the
                        # bound.  The next probe at this bound is cold.
                        try_warm = False
                        outcome = "warm-miss"
                else:
                    model, outcome, backend = self._cold_probe(
                        p, variables, objective, bound, deadline_t,
                        tenant)
                if model is not None:
                    best = model
                    new_cost = objective.value(best)
                    delta = cost - new_cost
                    cost = new_cost
                    improvements += 1
                    self._c_improvements.inc()
                    outcome = "improved"
                    sp.set(improvement=delta)
                    try_warm = req.warm
                    if cost <= floor:
                        proof = "floor"
                        self._c_proofs.inc(label="floor")
                elif outcome == "unsat":
                    proof = "unsat_probe"
                    self._c_proofs.inc(label="unsat_probe")
                elif outcome == "budget":
                    reason = "probe-budget"
                sp.set(backend=backend, outcome=outcome)
            reg.event("optimize", iteration=iterations, mode=mode,
                      backend=backend, outcome=outcome, bound=bound,
                      objective=cost, improvement=delta,
                      dur_s=round(time.perf_counter() - t0, 6),
                      tenant=tenant)

        canonical = self._canonicalize(p, objective, cost)
        if canonical is not None:
            best = canonical
            cost = objective.value(best)
        out["status"] = "optimal" if proof is not None else "degraded"
        out["optimal"] = proof is not None
        out["proof"] = proof
        if reason is not None:
            out["reason"] = reason
        if canonical is None:
            out["canonical"] = False
        out["iterations"] = iterations
        out["improvements"] = improvements
        out["objective"] = cost
        selected = [str(variables[i].identifier)
                    for i in np.nonzero(best)[0]]
        out["selected"] = selected
        if req.query == "upgrade":
            chosen = set(selected)
            out["missing_prefer"] = [i for i in req.prefer
                                     if i not in chosen]
            installed = set(req.installed)
            out["touched"] = (len(installed - chosen)
                              + len(chosen - installed))
        return out

    # ------------------------------------------------------------ probes

    def _submit(self, family: List[Variable],
                deadline_t: Optional[float], tenant: str,
                max_steps: Optional[int]):
        """One family through the scheduler's idle-priority optimize
        queue (portfolio-raced, preempted by live traffic)."""
        remaining = None
        if deadline_t is not None:
            remaining = max(deadline_t - time.monotonic(), 0.001)
        return self.scheduler.submit_optimize(
            [family], deadline_s=remaining, max_steps=max_steps,
            tenant=tenant)[0]

    def _host_probe(self, p, objective: Objective, bound: int,
                    seed: Optional[np.ndarray] = None,
                    cone: Optional[np.ndarray] = None):
        """One bounded probe on the host objective engine — the one
        backend with ``bound_weights`` (mixed-sign) support, and the
        only engine that can warm-start from a pinned cone.  A fresh
        engine per probe keeps the step budget per-probe, matching the
        scheduler's per-dispatch budgets.  Returns ``(status, model)``
        with status ``sat``/``unsat``/``budget`` — the unsat/budget
        distinction matters because only a COMPLETE cold probe's unsat
        is an optimality proof."""
        eng = HostEngine(p, max_steps=self.iter_budget)
        try:
            ok, m = eng.solve_bounded(objective.signed,
                                      objective.bound_for(bound),
                                      seed_model=seed, cone_mask=cone)
        except Incomplete:
            return "budget", None
        if not ok:
            return "unsat", None
        return "sat", np.asarray(m[: p.n_vars] > 0, dtype=bool)

    def _cold_probe(self, p, variables: List[Variable],
                    objective: Objective, bound: int,
                    deadline_t: Optional[float], tenant: str):
        """One complete probe at ``bound``.  Returns ``(model-or-None,
        outcome, backend)`` where outcome is ``improved`` (model
        found), ``unsat`` (definitive — the caller's optimality proof),
        or ``budget``.  Routing is registry-driven: a unit-positive
        objective lowers to a plain AtMost family served through the
        scheduler (raced across ``optimize_candidates``); otherwise the
        host objective engine — the single ``bound_weights``
        candidate — runs it inline."""
        native = native_bound_variables(variables, objective,
                                        objective.bound_for(bound))
        signed = not objective.unit_positive
        names, _ = engine_registry.optimize_candidates(
            "m", signed=signed)
        if native is not None and self.scheduler is not None \
                and len(names) > 1:
            res = self._submit(list(native), deadline_t, tenant,
                               self.iter_budget)
            if isinstance(res, dict):
                model = np.fromiter(
                    (bool(res[v.identifier]) for v in variables),
                    dtype=bool, count=p.n_vars)
                return model, "improved", "sched"
            if isinstance(res, NotSatisfiable):
                return None, "unsat", "sched"
            return None, "budget", "sched"
        status, m = self._host_probe(p, objective, bound)
        if status == "sat":
            return m, "improved", "host"
        return None, status, "host"

    def _canonicalize(self, p, objective: Objective,
                      cost: int) -> Optional[np.ndarray]:
        """The canonical answer at the final cost: a cold bounded solve
        whose lex-least model is THE tie-break the differential oracle
        pins.  Every model at the proven-optimal bound has exactly the
        optimal cost, so lex-least-under-bound = lex-least-among-
        optima.  None on budget exhaustion — the caller keeps the raw
        best model and flags it non-canonical."""
        status, m = self._host_probe(p, objective, cost)
        if status != "sat":
            return None
        return m
