"""Consistent-hash affinity ring (ISSUE 15, piece 1).

**Affinity key.**  Warm state is FAMILY-scoped: the clause-set index
buckets entries by decode vocabulary (:func:`deppy_tpu.incremental.
clauseset.vocab_key`), and a catalog churn delta keeps the family's
variable identifiers while changing its constraints.  Routing on the
exact canonical fingerprint would therefore scatter one family's churn
stream across replicas — every delta is a new fingerprint — so the
affinity key hashes the ORDERED variable-identifier list instead:
identical for every delta of a family, distinct across families, and
computable from the request document alone (no encode needed on the
router's hot path).

**Ring.**  Each replica owns ``vnodes`` points on a 64-bit ring
(sha256 of ``"replica#i"``); a key routes to the first point clockwise
from its own hash.  Removing a replica (death, drain) reassigns only
its arcs — every other family keeps its replica, which is exactly the
property that preserves the fleet's warm tier under membership churn.
``route(key, exclude=...)`` walks past excluded owners, so the retry /
handoff successor of a key is simply its route with the failed replica
excluded.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, List, Optional, Sequence, Tuple

DEFAULT_VNODES = 64

# Identifier-list separator: matches the canonical fingerprint's vocab
# encoding (sched/cache.py) so no identifier ambiguity ("a" + "bc" vs
# "ab" + "c") can alias two families onto one key.
_SEP = "\x1f"


def _point(token: str) -> int:
    return int.from_bytes(
        hashlib.sha256(token.encode()).digest()[:8], "big")


def affinity_key(identifiers: Iterable[str]) -> str:
    """The family affinity key: hex digest over the ORDERED variable
    identifiers.  Order matters — the decode vocabulary is ordered, and
    two requests naming the same ids in different orders render
    different responses (they are different families)."""
    h = hashlib.sha256(_SEP.join(str(i) for i in identifiers).encode())
    return h.hexdigest()


def doc_affinity_keys(doc) -> List[Optional[str]]:
    """Per-problem affinity keys of one ``/v1/resolve`` document
    (``{"variables": [...]}`` or ``{"problems": [...]}``), WITHOUT
    encoding: just the ``id`` fields in order.  A problem too malformed
    to name its ids keys ``None`` — the router still forwards it (to
    the ring's default arc) and the replica renders the same 400 a
    single server would."""
    if not isinstance(doc, dict):
        return [None]
    raw = doc.get("problems") if "problems" in doc else [doc]
    if not isinstance(raw, list):
        return [None]
    out: List[Optional[str]] = []
    for p in raw:
        try:
            out.append(affinity_key(v["id"] for v in p["variables"]))
        except (TypeError, KeyError):
            out.append(None)
    return out or [None]


class HashRing:
    """Immutable consistent-hash ring over replica addresses.

    Membership changes (drain, death) are expressed at route time via
    ``exclude`` rather than by rebuilding the ring: the surviving
    owner of a key under exclusion is then BY CONSTRUCTION the replica
    that inherits the excluded owner's arc for that key — the drain
    handoff and the retry-on-successor path use the same walk."""

    def __init__(self, replicas: Sequence[str],
                 vnodes: int = DEFAULT_VNODES):
        self.replicas: Tuple[str, ...] = tuple(dict.fromkeys(replicas))
        if not self.replicas:
            raise ValueError("HashRing requires at least one replica")
        self.vnodes = max(int(vnodes), 1)
        points: List[Tuple[int, str]] = []
        for rep in self.replicas:
            for i in range(self.vnodes):
                points.append((_point(f"{rep}#{i}"), rep))
        points.sort()
        self._points = points
        self._hashes = [p for p, _ in points]

    def route(self, key: Optional[str],
              exclude: Iterable[str] = ()) -> Optional[str]:
        """The replica owning ``key``, skipping ``exclude`` members;
        None when every replica is excluded.  ``key=None`` (a problem
        whose ids could not be read) routes to the ring's first arc —
        deterministic, so the byte-identity pins hold."""
        dead = frozenset(exclude)
        n = len(self._points)
        start = (bisect.bisect_right(self._hashes, _point(key))
                 % n if key is not None else 0)
        seen = set()
        for off in range(n):
            rep = self._points[(start + off) % n][1]
            if rep in dead or rep in seen:
                seen.add(rep)
                continue
            return rep
        return None

    def successor(self, key: Optional[str], owner: str,
                  exclude: Iterable[str] = ()) -> Optional[str]:
        """The replica inheriting ``key`` when ``owner`` is gone —
        its route with the owner (and any other exclusions) removed."""
        return self.route(key, exclude=set(exclude) | {owner})
