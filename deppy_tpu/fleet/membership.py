"""Elastic fleet membership (ISSUE 17 tentpole, piece 1): epoch-versioned
ring changes with warm-state-first joins.

PR 15 deliberately kept the ring immutable — membership changes were
expressed at route time (``exclude``) and scale-out warmed only through
a manual ``POST /fleet/drain``.  This module makes membership itself a
first-class, **epoch-versioned** value so the fleet can reshape under
load:

  * **Runtime join** (:func:`join_replica`, ``POST /fleet/join``): a
    new replica announces itself and the router streams the warm state
    the joiner will inherit from its arc predecessors — the PR 15
    snapshot machinery (``split_snapshot`` against the *prospective*
    ring), re-sealed into bounded, individually checksummed chunks so a
    truncated transfer is rejected loudly and resumes per chunk
    (``import_warm_state`` is idempotent; re-sending a chunk can never
    double-import).  Only once the whole stream lands does the **atomic
    arc flip** happen: the ring is rebuilt with the joiner and swapped
    under the router lock, and the membership epoch increments.  A
    failed stream leaves membership exactly as it was — the joiner
    simply is not a member — so a join can never expose a cold arc that
    the fault-free fleet would have served warm.
  * **Leave = drain**: ``Router.drain`` keeps its PR 15 handoff; in
    elastic mode the drained replica additionally leaves the ring
    itself and the epoch increments, so peer routers gossip the
    removal instead of re-probing a ghost forever.  Replicas trigger it
    automatically on graceful shutdown (``Server.shutdown``).
  * **Peer gossip** (:func:`membership_view` / :func:`reconcile`,
    ``POST /fleet/sync``): routers on a static ``--peers`` list
    exchange epoch-versioned ring views.  The higher epoch wins
    wholesale; same-epoch divergence resolves by a deterministic
    tiebreak (member count, then a hash of the sorted member list) so
    two routers that each committed a different change converge without
    flapping.  Health-probe verdicts (``dead``) merge only from a view
    at >= the local epoch — a stale router cannot resurrect or bury a
    replica the current epoch already re-decided.

``DEPPY_TPU_FLEET=static`` switches all of this off and restores the
PR 15 static-ring surface byte for byte: the join/sync/policy endpoints
404 and ``/fleet/replicas`` carries no epoch.
"""

from __future__ import annotations

import hashlib
import json
from typing import Iterator, List, Tuple

from .. import config, faults, telemetry
from .ring import HashRing
from .snapshot import _seal, split_snapshot, verify_snapshot

DEFAULT_MEMBERSHIP = "elastic"
DEFAULT_JOIN_CHUNK = 64
DEFAULT_JOIN_RETRIES = 2

_STATIC = ("static", "off", "0", "false", "no")
_ELASTIC = ("elastic", "on", "1", "true", "yes")


def membership_mode(value=None) -> str:
    """Normalize the fleet membership mode ('elastic' | 'static')."""
    if value is None:
        value = config.env_str("DEPPY_TPU_FLEET") or DEFAULT_MEMBERSHIP
    mode = str(value).strip().lower() or DEFAULT_MEMBERSHIP
    if mode in _STATIC:
        return "static"
    if mode in _ELASTIC:
        return "elastic"
    raise ValueError(
        f"unknown fleet membership mode {value!r} "
        "(want 'elastic' or 'static'; DEPPY_TPU_FLEET / --membership)")


def _validate_address(address) -> str:
    if not isinstance(address, str) or ":" not in address:
        raise ValueError('join requires {"replica": "host:port"}')
    _, _, port = address.rpartition(":")
    try:
        int(port)
    except ValueError:
        raise ValueError(
            f"invalid replica address {address!r} (want host:port)") from None
    return address


def iter_chunks(shard: dict, chunk_entries: int) -> Iterator[dict]:
    """Split one sealed warm-state shard into bounded mini-snapshots.

    Each chunk is re-sealed (its own version + checksum over canonical
    JSON), so the joiner verifies every chunk independently — a
    truncated or corrupted chunk fails ITS import and re-sends whole,
    never poisoning the entries that already landed.
    """
    has_sessions = "sessions" in shard
    entries: List[Tuple[str, dict]] = (
        [("index", e) for e in shard.get("index") or []]
        + [("cache", e) for e in shard.get("cache") or []]
        + [("sessions", e) for e in shard.get("sessions") or []])
    step = max(int(chunk_entries), 1)
    for i in range(0, len(entries), step):
        part = entries[i:i + step]
        yield _seal([e for kind, e in part if kind == "index"],
                    [e for kind, e in part if kind == "cache"],
                    sessions=([e for kind, e in part
                               if kind == "sessions"]
                              if has_sessions else None))


def _deliver_chunk(router, address: str, chunk: dict, retries: int) -> None:
    """POST one sealed chunk to the joiner, resending on failure.

    Resumable by construction: ``import_warm_state`` is idempotent
    (live state wins), so a chunk whose POST failed mid-flight re-sends
    whole without double-importing what already landed.
    """
    payload = json.dumps(chunk).encode("utf-8")
    last = None
    for _ in range(max(int(retries), 0) + 1):
        try:
            # Scripted chunk-stream fault point: a rule here makes one
            # (or every) delivery fail without touching the transport.
            faults.inject("fleet.join_stream")
            status, body, _ = router.forward(
                address, "POST", "/debug/warmstate", payload,
                {"Content-Type": "application/json"})
        except (OSError, faults.InjectedFault) as exc:
            last = exc
            continue
        if status == 200:
            return
        last = OSError(
            f"joiner {address} rejected warm-state chunk "
            f"(HTTP {status}): {body[:200]!r}")
    raise OSError(
        f"join stream to {address} failed after "
        f"{max(int(retries), 0) + 1} attempt(s): {last}")


def join_replica(router, address: str) -> dict:
    """Admit ``address`` to the fleet: stream its inherited warm state,
    then atomically flip its arcs live.

    The prospective ring (current members + joiner) decides which
    entries move: for every live donor we fetch ``GET /debug/warmstate``
    (PR 15 snapshot export), keep the shard ``split_snapshot`` assigns
    to the joiner under the prospective ring — exactly the arcs the
    joiner steals — and stream it over in checksummed chunks.  Nothing
    about live membership mutates until every chunk has landed; the
    flip itself (ring swap + epoch bump) happens in one critical
    section, so no request ever routes to a half-warmed joiner.
    """
    from .router import _Replica

    if not router.elastic:
        raise ValueError(
            "fleet membership is static (DEPPY_TPU_FLEET=static): "
            "POST /fleet/join is disabled")
    address = _validate_address(address)
    with router._lock:
        members = list(router.ring.replicas)
        vnodes = router.ring.vnodes
        state = router._replicas.get(address)
        if address in members and state is not None and not state.drained:
            raise ValueError(f"replica {address} is already a fleet member")
        unroutable = set(router._unroutable_locked())
    prospective = HashRing(
        [m for m in members if m != address] + [address], vnodes=vnodes)
    chunk_entries = config.env_int(
        "DEPPY_TPU_FLEET_JOIN_CHUNK", DEFAULT_JOIN_CHUNK, strict=False)
    retries = config.env_int(
        "DEPPY_TPU_FLEET_JOIN_RETRIES", DEFAULT_JOIN_RETRIES, strict=False)
    donors = [m for m in members if m != address and m not in unroutable]
    chunks = entries = 0
    for donor in donors:
        status, body, _ = router.forward(donor, "GET", "/debug/warmstate",
                                         None)
        if status != 200:
            continue  # warm tier off on this donor: nothing to inherit
        snapshot = verify_snapshot(json.loads(body))
        shard = split_snapshot(
            snapshot, lambda aff: prospective.route(aff)).get(address)
        if shard is None:
            continue  # none of this donor's arcs move to the joiner
        for chunk in iter_chunks(shard, chunk_entries):
            _deliver_chunk(router, address, chunk, retries)
            chunks += 1
            entries += len(chunk["index"]) + len(chunk["cache"]) \
                + len(chunk.get("sessions") or [])
    # The atomic arc flip: membership mutates ONLY here, after the
    # whole stream landed.  A scripted fault at this point proves the
    # failure mode is "joiner never admitted", not "cold arcs live".
    faults.inject("fleet.arc_flip")
    with router._lock:
        router.ring = prospective
        state = router._replicas.get(address)
        if state is None:
            router._replicas[address] = _Replica(address)
        else:
            state.drained = False
            state.dead = False
            state.failures = 0
        router.epoch += 1
        epoch = router.epoch
    if router._c_joins is not None:
        router._c_joins.inc()
        router._c_join_chunks.inc(chunks)
    telemetry.default_registry().event(
        "fault", fault="fleet_join", replica=address, epoch=epoch,
        chunks=chunks, entries=entries, donors=len(donors))
    return {"replica": address, "epoch": epoch, "donors": len(donors),
            "chunks": chunks, "warm_entries": entries}


def membership_view(router) -> dict:
    """This router's epoch-versioned ring view, as gossiped to peers."""
    with router._lock:
        return {
            "epoch": router.epoch,
            "vnodes": router.ring.vnodes,
            "members": list(router.ring.replicas),
            "dead": sorted(a for a, st in router._replicas.items()
                           if st.dead and not st.drained),
            "drained": sorted(a for a, st in router._replicas.items()
                              if st.drained),
        }


def _tiebreak(members) -> Tuple[int, str]:
    """Deterministic same-epoch winner: more members, then member-set
    hash — both routers compute the same order, so a partitioned pair
    that each committed a different change converges without flapping.
    """
    canon = sorted(members)
    digest = hashlib.sha256("\x1f".join(canon).encode("utf-8")).hexdigest()
    return (len(canon), digest)


def reconcile(router, view) -> dict:
    """Merge a peer's membership view into this router; return ours.

    Adoption is wholesale and epoch-gated: a strictly newer epoch (or a
    same-epoch tiebreak winner with a different member set) replaces
    the ring, member table, and drained flags in one critical section.
    Health verdicts (``dead``) OR-merge only from a view at >= the
    local epoch — marking dead is safe (probes revive a live replica on
    the next success), but only within the same membership generation.
    """
    from .router import _Replica

    if not router.elastic:
        raise ValueError(
            "fleet membership is static (DEPPY_TPU_FLEET=static): "
            "POST /fleet/sync is disabled")
    if not isinstance(view, dict):
        raise ValueError("fleet sync view must be a JSON object")
    try:
        epoch = int(view["epoch"])
        members = [str(m) for m in view["members"]]
    except (KeyError, TypeError, ValueError):
        raise ValueError(
            'fleet sync view requires integer "epoch" and a '
            '"members" list') from None
    if not members:
        raise ValueError("fleet sync view names no members")
    adopted = False
    newly_dead: List[str] = []
    with router._lock:
        local = list(router.ring.replicas)
        wins = epoch > router.epoch or (
            epoch == router.epoch and set(members) != set(local)
            and _tiebreak(members) > _tiebreak(local))
        if wins:
            drained = {str(a) for a in view.get("drained") or []}
            router.ring = HashRing(members, vnodes=router.ring.vnodes)
            for m in members:
                if m not in router._replicas:
                    router._replicas[m] = _Replica(m)
            for addr, st in router._replicas.items():
                if addr in drained or addr not in members:
                    # Drained away (or removed) under the adopted
                    # epoch: retire it here too instead of probing a
                    # ghost.
                    st.drained = True
                elif st.drained:
                    st.drained = False  # re-joined under the newer epoch
            router.epoch = epoch
            adopted = True
        if epoch >= router.epoch:
            for addr in view.get("dead") or []:
                st = router._replicas.get(str(addr))
                if st is not None and not st.dead and not st.drained:
                    st.failures = max(st.failures, router.probe_failures)
                    st.dead = True
                    newly_dead.append(str(addr))
    reg = telemetry.default_registry()
    if adopted:
        reg.event("fault", fault="fleet_view_adopted", epoch=epoch,
                  members=sorted(members))
    for addr in newly_dead:
        router._c_transitions.inc(label="down")
        reg.event("fault", fault="fleet_replica_down", replica=addr,
                  via="peer_sync")
    return membership_view(router)
