"""SLO-burn-driven autoscale policy hook (ISSUE 17 tentpole, piece 3).

The fleet already measures the thing autoscalers usually have to guess:
every replica exports the per-tenant SLO burn rate (PR 10's
``deppy_tenant_burn_rate``, federated fleet-wide in PR 16).  Burn > 1
means a tenant is consuming error budget faster than its SLO window
replenishes it — sustained, the SLO fails.  This module turns that
signal into scale recommendations:

  * ``scale_up``    — the hottest replica burns above ``BURN_UP`` and
    no replica is cold enough to absorb a rebalance: the fleet needs
    another member (a runtime join via ``POST /fleet/join``).
  * ``rebalance``   — a replica burns above ``BURN_UP`` while another
    sits at or below ``BURN_DOWN``: capacity exists, placement is
    wrong.  Drain the hot replica; its arcs (and warm state) respread.
  * ``scale_down``  — every replica burns below ``BURN_DOWN`` with an
    idle queue: the coldest replica is the cheapest drain.
  * ``hold``        — burn within band, or no samples yet.

Execution stays operator-driven: the recommendation surfaces on
``GET /fleet/policy`` and as ``fleet_policy`` telemetry events, and
``deppy fleet scale --apply`` offers a local-process mode (spawn a
joining replica / drain the named victim) for the bench/soak harness.
:func:`decide` is pure — thresholds and burn samples in, decision out —
so the policy is unit-testable without a fleet.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import faults, telemetry

DEFAULT_BURN_UP = 1.0
DEFAULT_BURN_DOWN = 0.25


def decide(per_replica_burn: Dict[str, Dict[str, float]],
           queue_depth: float, burn_up: float, burn_down: float) -> dict:
    """Pure decision core: per-replica-per-tenant burn rates in,
    ``{decision, target, reasons}`` out.

    Each replica is scored by its hottest tenant (fairness means the
    worst-served tenant is the one the SLO answers for), and the
    thresholds compare against that peak.
    """
    hot = {rep: max(burn.values())
           for rep, burn in per_replica_burn.items() if burn}
    reasons: List[str] = []
    if not hot:
        return {"decision": "hold", "target": None,
                "reasons": ["no per-tenant burn samples yet"]}
    # Ties break on the address so two routers evaluating the same
    # scrape recommend the same victim.
    peak_rep = max(hot, key=lambda r: (hot[r], r))
    cold_rep = min(hot, key=lambda r: (hot[r], r))
    peak, cold = hot[peak_rep], hot[cold_rep]
    target: Optional[str] = None
    if peak > burn_up and cold > burn_down:
        decision = "scale_up"
        reasons.append(
            f"peak burn {peak:.3f} > {burn_up:g} on {peak_rep} and the "
            f"coldest replica ({cold_rep}, {cold:.3f}) is above "
            f"{burn_down:g} — no capacity to rebalance into")
    elif peak > burn_up:
        decision, target = "rebalance", peak_rep
        reasons.append(
            f"burn skew: {peak_rep} at {peak:.3f} > {burn_up:g} while "
            f"{cold_rep} sits at {cold:.3f} <= {burn_down:g} — drain "
            f"{peak_rep} so its arcs respread onto cold capacity")
    elif peak < burn_down and len(hot) > 1 and queue_depth <= 0:
        decision, target = "scale_down", cold_rep
        reasons.append(
            f"fleet-wide peak burn {peak:.3f} < {burn_down:g} across "
            f"{len(hot)} replicas with an idle queue — {cold_rep} is "
            f"the cheapest drain")
    else:
        decision = "hold"
        reasons.append(f"burn within band ({cold:.3f}..{peak:.3f})")
    return {"decision": decision, "target": target, "reasons": reasons}


def evaluate(router) -> dict:
    """One policy evaluation over a live fleet scrape.

    Scrapes every routable replica (PR 16 federation), extracts each
    replica's per-tenant burn rates, and runs :func:`decide` against
    the ``DEPPY_TPU_FLEET_BURN_UP``/``_DOWN`` thresholds.  Emits a
    ``fleet_policy`` telemetry event and counts the decision on
    ``deppy_fleet_policy_evals_total``.
    """
    from ..obs import federate

    scrapes = federate.collect(router)
    rollups = federate.fleet_rollups(scrapes)
    per_replica_burn: Dict[str, Dict[str, float]] = {}
    for replica, text in scrapes:
        samples = federate.parse_samples(text)
        burn = federate._by_label(samples, "deppy_tenant_burn_rate",
                                  "tenant")
        per_replica_burn[replica] = {t: round(v, 6)
                                     for t, v in burn.items()}
    burn_up = faults.env_float("DEPPY_TPU_FLEET_BURN_UP",
                               DEFAULT_BURN_UP, warn=True)
    burn_down = faults.env_float("DEPPY_TPU_FLEET_BURN_DOWN",
                                 DEFAULT_BURN_DOWN, warn=True)
    out = decide(per_replica_burn, rollups.get("queue_depth") or 0.0,
                 burn_up, burn_down)
    out.update({
        "epoch": router.epoch,
        "replicas": len(scrapes),
        "burn_up": burn_up,
        "burn_down": burn_down,
        "per_replica_burn": per_replica_burn,
        "tenant_burn_rate": rollups.get("tenant_burn_rate") or {},
        "warm_hit_ratio": rollups.get("warm_hit_ratio"),
        "queue_depth": rollups.get("queue_depth"),
    })
    if router._c_policy_evals is not None:
        router._c_policy_evals.inc(label=out["decision"])
    telemetry.default_registry().event(
        "fleet_policy", decision=out["decision"], target=out["target"],
        epoch=router.epoch, replicas=len(scrapes),
        reasons=out["reasons"])
    return out
