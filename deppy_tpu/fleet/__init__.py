"""Replica fleet with warm-state affinity routing (ISSUE 15 tentpole).

Every serving-side lever so far scales ONE process, and PR 9/14 made
in-process warm state (the clause-set index + exact-cache seeds) worth
3.9-6.7x — so naive load balancing across N replicas throws the warm
tier away.  This package makes N server processes behave like one warm
process:

  * :mod:`.ring` — a consistent-hash ring over replica addresses,
    keyed by the request's FAMILY affinity (the decode-vocabulary
    identifiers, which churn deltas of one family share even though
    their exact fingerprints differ), so a family's whole churn stream
    concentrates on the replica holding its warm seeds;
  * :mod:`.router` — the ``deppy route`` front-end: speaks the
    existing HTTP surface, routes ``/v1/resolve`` per problem over the
    ring, health-probes every replica (a dead replica's arc reassigns
    and an in-flight request retries once on the ring successor),
    fans catalog publishes out to every replica's speculative tier,
    and orchestrates the drain handoff;
  * :mod:`.snapshot` — versioned, integrity-checked serialization of a
    replica's warm state (clause-set index entries + exact-cache SAT
    seeds), so a draining replica bequeaths its warm tier to the
    replicas inheriting its ring arcs instead of forcing the fleet
    cold.

The scheduler side of the fleet story — per-tenant weighted-fair
admission and priority lanes replacing the global-depth 503 — lives in
:mod:`deppy_tpu.sched.scheduler` (``DEPPY_TPU_SCHED_FAIR``).

ISSUE 17 makes the ring breathe: :mod:`.membership` adds runtime joins
(``POST /fleet/join`` — chunked warm-state streaming, then an atomic
arc flip), drain-as-leave epoch bumps, and epoch-versioned peer gossip
(``POST /fleet/sync``); :mod:`.policy` turns the federated per-tenant
SLO burn rate into ``scale_up``/``scale_down``/``rebalance``
recommendations (``GET /fleet/policy``).  ``DEPPY_TPU_FLEET=static``
restores the PR 15 static-ring surface byte for byte.
"""

from .membership import (join_replica, membership_mode,  # noqa: F401
                         membership_view, reconcile)
from .policy import decide as policy_decide  # noqa: F401
from .ring import HashRing, affinity_key, doc_affinity_keys  # noqa: F401
from .router import Router  # noqa: F401
from .snapshot import (SNAPSHOT_VERSION, SnapshotFormatError,  # noqa: F401
                       export_warm_state, import_warm_state)
