"""The affinity router front-end (ISSUE 15, piece 2): ``deppy route``.

A standalone process speaking the EXISTING HTTP surface — clients point
at the router instead of a replica and change nothing else:

  * ``POST /v1/resolve`` routes per problem over the consistent-hash
    ring (:mod:`.ring`), so a family's churn stream always lands on the
    replica holding its warm seeds.  A request whose problems map to
    one replica forwards byte-for-byte; a mixed batch splits into
    per-replica sub-batches and the results merge back in input order
    — either way the body equals what a single replica would serve.
  * ``POST /v1/catalog/publish`` fans out to EVERY live replica: each
    replica's speculative tier must see the catalog delta or its warm
    families go stale (``deppy_fleet_publish_fanout_total``).
  * ``POST /v1/resolve/preview`` fans out too and concatenates the
    per-replica previews — retained families are partitioned by
    affinity, so the union is the fleet's answer.
  * ``GET /metrics`` / ``GET /fleet/replicas`` expose routing counts,
    per-replica health, and breaker state.
  * ``POST /fleet/drain`` runs the warm-state handoff: fetch the
    draining replica's snapshot (``GET /debug/warmstate``), split it by
    each entry's family affinity across the replicas inheriting its
    ring arcs, and POST each shard to its inheritor — then retire the
    replica from routing.  The operator SIGTERMs it afterwards.

**Health.**  A background prober hits every replica on an interval;
``probe_failures`` consecutive transport failures open that replica's
breaker (dead: its arcs reassign on the ring), and a later successful
probe closes it (the arcs return — warm state it accumulated before
dying is still there).  A transport failure on a live forward charges
the same breaker and the request retries ONCE on the key's ring
successor, so a replica crash degrades only its in-flight requests by
one retry, never to client-visible errors.

**Elastic membership (ISSUE 17).**  With ``DEPPY_TPU_FLEET=elastic``
(the default) the ring is no longer fixed at boot: ``POST /fleet/join``
admits a new replica after streaming it the warm state it inherits
(:mod:`.membership` — the atomic arc flip), a drain additionally
removes the replica from the ring and bumps the membership epoch, and
routers on a static ``--peers`` list gossip epoch-versioned ring views
over ``POST /fleet/sync`` so clients can hit any router.
``GET /fleet/policy`` surfaces the SLO-burn autoscale recommendation
(:mod:`.policy`).  ``DEPPY_TPU_FLEET=static`` restores the PR 15
surface byte for byte: those endpoints 404 and the ring never rebuilds.
"""

from __future__ import annotations

import json
import random
import threading
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler
from typing import Dict, List, Optional, Tuple

from .. import config, faults, telemetry
from .ring import DEFAULT_VNODES, HashRing, doc_affinity_keys
from .snapshot import SnapshotFormatError, split_snapshot, verify_snapshot

DEFAULT_PROBE_INTERVAL_S = 2.0
DEFAULT_PROBE_FAILURES = 3
DEFAULT_PROBE_JITTER = 0.2
DEFAULT_SYNC_INTERVAL_S = 2.0
# Forwarded solves can legitimately take minutes (budget escalation on
# a cold device path); transport-level hangs are the prober's job.
FORWARD_TIMEOUT_S = 600.0
PROBE_TIMEOUT_S = 2.0

# Request headers forwarded to replicas (ISSUE 15 satellite: trace
# identity must survive the hop so a fleet-routed request reconstructs
# as ONE tree in `deppy trace`), and response headers echoed back.
FORWARD_HEADERS = ("Content-Type", "traceparent", "X-Deppy-Request-Id",
                   "X-Deppy-Tenant", "X-Deppy-Deadline-S",
                   "X-Deppy-Timings", "X-Deppy-Session")
ECHO_HEADERS = ("X-Deppy-Request-Id", "traceparent", "Retry-After")


class _Replica:
    """One replica's health/breaker state (guarded by Router._lock)."""

    __slots__ = ("address", "failures", "dead", "drained")

    def __init__(self, address: str):
        self.address = address
        self.failures = 0
        self.dead = False
        self.drained = False


def _parse_replicas(spec) -> List[str]:
    if isinstance(spec, str):
        spec = [s for s in (t.strip() for t in spec.split(",")) if s]
    out = list(dict.fromkeys(spec or []))
    if not out:
        raise ValueError(
            "fleet router requires at least one replica address "
            "(--replicas host:port[,host:port...] / "
            "DEPPY_TPU_FLEET_REPLICAS)")
    return out


def _split_host_port(address: str) -> Tuple[str, int]:
    host, _, port = address.rpartition(":")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    return host or "127.0.0.1", int(port)


def _peer_exchange(peer: str, payload: bytes,
                   timeout: float = PROBE_TIMEOUT_S * 2
                   ) -> Tuple[int, bytes]:
    """One ``POST /fleet/sync`` exchange with a peer router."""
    host, port = _split_host_port(peer)
    conn = HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/fleet/sync", body=payload,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class Router:
    """The replica-fleet affinity router."""

    def __init__(
        self,
        bind_address: str = ":8079",
        replicas=None,
        vnodes: Optional[int] = None,
        probe_interval_s: Optional[float] = None,
        probe_failures: Optional[int] = None,
        policy: str = "affinity",
        max_body_bytes: int = 8 * 1024 * 1024,
        obs_sink: Optional[str] = None,
        membership: Optional[str] = None,
        peers=None,
        probe_jitter: Optional[float] = None,
        sync_interval_s: Optional[float] = None,
    ):
        from ..analysis import lockdep

        if replicas is None:
            replicas = config.env_str("DEPPY_TPU_FLEET_REPLICAS")
        addresses = _parse_replicas(replicas)
        if vnodes is None:
            vnodes = config.env_int("DEPPY_TPU_FLEET_VNODES",
                                    DEFAULT_VNODES, strict=False)
        if probe_interval_s is None:
            probe_interval_s = faults.env_float(
                "DEPPY_TPU_FLEET_PROBE_INTERVAL_S",
                DEFAULT_PROBE_INTERVAL_S, warn=True)
        if probe_failures is None:
            probe_failures = config.env_int(
                "DEPPY_TPU_FLEET_PROBE_FAILURES",
                DEFAULT_PROBE_FAILURES, strict=False)
        if policy not in ("affinity", "roundrobin"):
            raise ValueError(
                f"unknown routing policy {policy!r} "
                "(want 'affinity' or 'roundrobin')")
        # ``roundrobin`` exists for the bench artifact only: it is the
        # warm-state-destroying baseline the affinity ring is measured
        # against (bench.py --workload fleet).
        self.policy = policy
        self.ring = HashRing(addresses, vnodes=vnodes)
        self.probe_interval_s = max(float(probe_interval_s or 0.0), 0.0)
        self.probe_failures = max(int(probe_failures), 1)
        self.max_body_bytes = max_body_bytes
        # Elastic membership (ISSUE 17): 'elastic' arms runtime joins
        # (POST /fleet/join), drain-as-leave ring removal, peer gossip
        # (POST /fleet/sync) and GET /fleet/policy; 'static'
        # (DEPPY_TPU_FLEET=static) keeps the PR 15 immutable-ring
        # surface byte for byte — those endpoints 404 and the epoch
        # never surfaces.
        from .membership import membership_mode

        self.membership = membership_mode(membership)
        self.epoch = 1
        if peers is None:
            peers = config.env_str("DEPPY_TPU_FLEET_PEERS")
        if isinstance(peers, str):
            peers = [p for p in (t.strip() for t in peers.split(","))
                     if p]
        self.peers: List[str] = list(dict.fromkeys(peers or []))
        if probe_jitter is None:
            probe_jitter = faults.env_float(
                "DEPPY_TPU_FLEET_PROBE_JITTER", DEFAULT_PROBE_JITTER,
                warn=True)
        self.probe_jitter = min(max(float(probe_jitter or 0.0), 0.0),
                                1.0)
        if sync_interval_s is None:
            sync_interval_s = faults.env_float(
                "DEPPY_TPU_FLEET_SYNC_INTERVAL_S",
                DEFAULT_SYNC_INTERVAL_S, warn=True)
        self.sync_interval_s = max(float(sync_interval_s or 0.0), 0.0)
        self._lock = lockdep.make_lock("fleet.router")
        self._replicas: Dict[str, _Replica] = {
            a: _Replica(a) for a in addresses}
        self._rr_next = 0
        self.registry = telemetry.Registry()
        r = self.registry
        self._c_routed = r.counter(
            "deppy_fleet_routed_total",
            "Problems routed, by replica.", labelname="replica")
        self._c_requests = r.counter(
            "deppy_fleet_requests_total",
            "Requests handled by the router, by endpoint.",
            labelname="endpoint")
        self._c_retries = r.counter(
            "deppy_fleet_retries_total",
            "Forwards retried on the ring successor after a replica "
            "transport failure.")
        self._c_probe_failures = r.counter(
            "deppy_fleet_probe_failures_total",
            "Health-probe transport failures, by replica.",
            labelname="replica")
        self._c_transitions = r.counter(
            "deppy_fleet_replica_transitions_total",
            "Replica breaker transitions (up->down and down->up).",
            labelname="transition").preset("down", "up")
        self._c_fanout = r.counter(
            "deppy_fleet_publish_fanout_total",
            "Per-replica publish/preview fan-out forwards.")
        self._c_drains = r.counter(
            "deppy_fleet_drains_total",
            "Drain handoffs orchestrated (POST /fleet/drain).")
        self._c_handoff = r.counter(
            "deppy_fleet_handoff_entries_total",
            "Warm-state entries (index entries + cache seeds) handed "
            "off to arc inheritors during drains.")
        # Elastic-only families register only in elastic mode so the
        # static /metrics page stays byte-identical to PR 15.
        self._c_joins = self._c_join_chunks = None
        self._c_peer_syncs = self._c_policy_evals = None
        if self.elastic:
            self._c_joins = r.counter(
                "deppy_fleet_joins_total",
                "Runtime replica joins committed (atomic arc flips "
                "after a complete warm-state stream).")
            self._c_join_chunks = r.counter(
                "deppy_fleet_join_chunks_total",
                "Checksummed warm-state chunks streamed to joining "
                "replicas.")
            self._c_peer_syncs = r.counter(
                "deppy_fleet_peer_syncs_total",
                "Membership gossip exchanges with peer routers, by "
                "outcome.", labelname="outcome").preset("ok", "error")
            self._c_policy_evals = r.counter(
                "deppy_fleet_policy_evals_total",
                "Autoscale policy evaluations (GET /fleet/policy), by "
                "decision.", labelname="decision")
        # Fleet observability plane (ISSUE 16): --obs-sink /
        # DEPPY_TPU_OBS_SINK names the merged fleet JSONL sink.
        # Replicas batch-push their sink events to POST /fleet/telemetry
        # and each lands replica-stamped; the router's OWN events
        # (replica up/down faults on the default registry,
        # router.forward spans on this registry) join the same sink via
        # forwarders stamped "router", so `deppy trace --fleet` rebuilds
        # a routed request as one tree from this single file.
        if obs_sink is None:
            obs_sink = config.env_str("DEPPY_TPU_OBS_SINK")
        self.aggregator = None
        self._obs_forwarders: list = []
        if obs_sink:
            from ..obs.aggregate import ROUTER_REPLICA, Aggregator

            self.aggregator = Aggregator(obs_sink, registry=self.registry)

            def _to_sink(ev, _agg=self.aggregator):
                _agg.ingest_event(ROUTER_REPLICA, ev)

            for reg in (self.registry, telemetry.default_registry()):
                reg.add_forwarder(_to_sink)
                self._obs_forwarders.append((reg, _to_sink))
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._sync_thread: Optional[threading.Thread] = None
        from ..service import _make_http_server, _parse_addr

        self._api = _make_http_server(_parse_addr(bind_address),
                                      _router_handler(self))
        self._threads: list = []

    # ------------------------------------------------------------ state

    @property
    def api_port(self) -> int:
        return self._api.server_address[1]

    @property
    def elastic(self) -> bool:
        return self.membership == "elastic"

    def _unroutable_locked(self) -> frozenset:
        return frozenset(a for a, st in self._replicas.items()
                         if st.dead or st.drained)

    def live_replicas(self) -> List[str]:
        with self._lock:
            dead = self._unroutable_locked()
            ring = self.ring
        return [a for a in ring.replicas if a not in dead]

    def target_for(self, key: Optional[str],
                   exclude=()) -> Optional[str]:
        """The replica serving ``key`` right now (health- and
        drain-aware).  Round-robin mode ignores the key — that is the
        point of the baseline."""
        with self._lock:
            dead = self._unroutable_locked() | frozenset(exclude)
            # Capture the ring inside the critical section: an elastic
            # arc flip swaps ``self.ring`` wholesale, and routing must
            # see one consistent (ring, health) pair.
            ring = self.ring
            if self.policy == "roundrobin":
                live = [a for a in ring.replicas if a not in dead]
                if not live:
                    return None
                target = live[self._rr_next % len(live)]
                self._rr_next += 1
                return target
        return ring.route(key, exclude=dead)

    def note_transport_failure(self, address: str) -> None:
        """A probe or live forward could not reach ``address``: charge
        its breaker; at the threshold the replica goes dead and its
        arcs reassign."""
        self._c_probe_failures.inc(label=address)
        with self._lock:
            st = self._replicas.get(address)
            if st is None or st.drained:
                return
            st.failures += 1
            if st.failures < self.probe_failures or st.dead:
                return
            st.dead = True
        self._c_transitions.inc(label="down")
        telemetry.default_registry().event(
            "fault", fault="fleet_replica_down", replica=address)

    def note_transport_success(self, address: str) -> None:
        with self._lock:
            st = self._replicas.get(address)
            if st is None:
                return
            st.failures = 0
            was_dead, st.dead = st.dead, False
        if was_dead:
            self._c_transitions.inc(label="up")
            telemetry.default_registry().event(
                "fault", fault="fleet_replica_up", replica=address)

    def replica_states(self) -> List[dict]:
        with self._lock:
            return [{"replica": st.address,
                     "dead": st.dead,
                     "drained": st.drained,
                     "consecutive_failures": st.failures}
                    for st in self._replicas.values()]

    # --------------------------------------------------------- transport

    def forward(self, address: str, method: str, path: str,
                body: Optional[bytes], headers: Optional[dict] = None,
                timeout: float = FORWARD_TIMEOUT_S):
        """One HTTP exchange with a replica; returns ``(status, body,
        headers)``.  Transport errors raise ``OSError`` AFTER charging
        the replica's breaker; HTTP error statuses are the replica's
        answer and pass through untouched."""
        faults.inject("fleet.forward")
        host, port = _split_host_port(address)
        try:
            conn = HTTPConnection(host, port, timeout=timeout)
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            hdrs = {k: v for k, v in resp.getheaders()}
            status = resp.status
            conn.close()
        except OSError:
            self.note_transport_failure(address)
            raise
        self.note_transport_success(address)
        return status, data, hdrs

    # ----------------------------------------------------------- probing

    def _jittered(self, base: float, rng=random.random) -> float:
        """One cycle's sleep with jitter (ISSUE 17 satellite — the
        lease ``renew_jitter`` pattern): ``base`` plus a random
        fraction of it, so a fleet of routers booted together does not
        thunder every replica (or peer) in lockstep phase."""
        return base + base * self.probe_jitter * rng()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self._jittered(self.probe_interval_s)):
            with self._lock:
                targets = [st.address for st in self._replicas.values()
                           if not st.drained]
            for address in targets:
                if self._stop.is_set():
                    return
                host, port = _split_host_port(address)
                try:
                    conn = HTTPConnection(host, port,
                                          timeout=PROBE_TIMEOUT_S)
                    # Any HTTP response — the path 404s on the API port
                    # — proves the process serves; readiness semantics
                    # stay with the replica's own probe listener.
                    conn.request("GET", "/healthz")
                    conn.getresponse().read()
                    conn.close()
                except OSError:
                    self.note_transport_failure(address)
                else:
                    self.note_transport_success(address)

    # ---------------------------------------------------- peer gossip

    def _sync_loop(self) -> None:
        while not self._stop.wait(self._jittered(self.sync_interval_s)):
            self.sync_peers()

    def sync_peers(self) -> dict:
        """One gossip round (ISSUE 17): push our membership view to
        every peer router and reconcile each answering view, so a
        join/leave committed on either side converges on both.
        Deliberately NOT :meth:`forward`: peers are not replicas — a
        down peer must not charge any replica breaker or trip the
        ``fleet.forward`` fault point."""
        from .membership import membership_view, reconcile

        payload = json.dumps({"view": membership_view(self)}).encode()
        out = {"peers": len(self.peers), "ok": 0, "errors": 0}
        for peer in self.peers:
            if self._stop.is_set():
                break
            try:
                faults.inject("router.peer_sync")
                status, body = _peer_exchange(peer, payload)
            except (OSError, faults.InjectedFault):
                if self._c_peer_syncs is not None:
                    self._c_peer_syncs.inc(label="error")
                out["errors"] += 1
                continue
            ok = False
            if status == 200:
                try:
                    remote = json.loads(body).get("view")
                    reconcile(self, remote)
                    ok = True
                except (ValueError, json.JSONDecodeError):
                    pass  # malformed peer answer: counted, next round
            if self._c_peer_syncs is not None:
                self._c_peer_syncs.inc(label="ok" if ok else "error")
            out["ok" if ok else "errors"] += 1
        return out

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        t = threading.Thread(target=self._api.serve_forever,
                             kwargs={"poll_interval": 0.05},
                             name="deppy-route", daemon=True)
        t.start()
        self._threads.append(t)
        if self.probe_interval_s > 0:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="deppy-route-probe",
                daemon=True)
            self._probe_thread.start()
        if self.elastic and self.peers and self.sync_interval_s > 0:
            self._sync_thread = threading.Thread(
                target=self._sync_loop, name="deppy-route-sync",
                daemon=True)
            self._sync_thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._threads:
            self._api.shutdown()
        self._api.server_close()
        self._threads = []
        t = self._probe_thread
        if t is not None:
            t.join(PROBE_TIMEOUT_S + self.probe_interval_s + 1.0)
            self._probe_thread = None
        t = self._sync_thread
        if t is not None:
            t.join(PROBE_TIMEOUT_S * 2 + self.sync_interval_s + 1.0)
            self._sync_thread = None
        for reg, fn in self._obs_forwarders:
            reg.remove_forwarder(fn)
        self._obs_forwarders = []
        if self.aggregator is not None:
            self.aggregator.close()
            self.aggregator = None

    def dump_fanout(self, body: Optional[bytes] = None) -> dict:
        """POST /debug/dump to every live replica (ISSUE 16): one
        operator signal — SIGUSR2 on the router, or its /debug/dump
        endpoint — flushes every replica's flight recorder into its
        sink/stream.  Returns the per-replica dump counts."""
        dumped: Dict[str, int] = {}
        errors: List[str] = []
        for address in self.live_replicas():
            try:
                status, data, _ = self.forward(
                    address, "POST", "/debug/dump", body or b"{}",
                    {"Content-Type": "application/json"},
                    timeout=PROBE_TIMEOUT_S * 5)
            except OSError:
                errors.append(address)
                continue
            if status != 200:
                errors.append(address)
                continue
            try:
                dumped[address] = int(json.loads(data).get("dumped", 0))
            except (ValueError, json.JSONDecodeError):
                dumped[address] = 0
        return {"dumped": dumped, "errors": errors}

    def gossip_routes(self, doc) -> int:
        """Fleet-wide route gossip (ISSUE 19): scan one ingested
        telemetry batch for live-learned routing rows and fan them out
        to every live replica's ``POST /v1/routes/learned``.  Only
        first-hand (``source == "live"``) adoptions re-broadcast —
        gossip-sourced ones stay put, and the learner's idempotent
        adopt terminates the echo at the origin — so a row crosses the
        fleet exactly once per discovery.  Returns replicas that
        accepted."""
        if not isinstance(doc, dict):
            return 0
        rows: Dict[str, str] = {}
        origin = doc.get("replica")
        for ev in doc.get("events") or []:
            if not isinstance(ev, dict) \
                    or ev.get("kind") != "route_learned" \
                    or ev.get("source") != "live":
                continue
            key, row = ev.get("key"), ev.get("row")
            if isinstance(key, str) and isinstance(row, str):
                rows[key] = row
        if not rows:
            return 0
        body = json.dumps({
            "rows": rows,
            "origin": origin if isinstance(origin, str) else None,
        }).encode("utf-8")
        accepted = 0
        for address in self.live_replicas():
            try:
                status, _, _ = self.forward(
                    address, "POST", "/v1/routes/learned", body,
                    {"Content-Type": "application/json"},
                    timeout=PROBE_TIMEOUT_S * 5)
            except OSError:
                continue
            if status == 200:
                accepted += 1
        if accepted:
            self.registry.counter(
                "deppy_fleet_route_gossip_total",
                "Learned routing-row broadcasts accepted by fleet "
                "replicas.").inc(accepted)
        return accepted

    # ------------------------------------------------------------- drain

    def drain(self, address: str) -> dict:
        """The warm-state handoff: snapshot the draining replica, split
        by family affinity across the surviving ring, deliver each
        shard, retire the replica from routing.  Raises ``ValueError``
        on an unknown replica, ``OSError``/:class:`SnapshotFormatError`
        when the snapshot cannot be fetched or verified (the replica
        stays routable — a failed drain must not silently blackhole an
        arc)."""
        with self._lock:
            st = self._replicas.get(address)
            if st is None:
                raise ValueError(f"unknown replica {address!r}")
            exclude = self._unroutable_locked() | {address}
        status, body, _ = self.forward(address, "GET", "/debug/warmstate",
                                       None)
        if status != 200:
            raise OSError(
                f"replica {address} warm-state export failed "
                f"(HTTP {status})")
        snapshot = verify_snapshot(json.loads(body))
        shards = split_snapshot(
            snapshot,
            lambda aff: self.ring.route(aff, exclude=exclude))
        delivered: Dict[str, dict] = {}
        entries = 0
        for owner, shard in shards.items():
            payload = json.dumps(shard).encode()
            s2, b2, _ = self.forward(
                owner, "POST", "/debug/warmstate", payload,
                {"Content-Type": "application/json"})
            if s2 != 200:
                raise OSError(
                    f"inheritor {owner} rejected warm-state shard "
                    f"(HTTP {s2}): {b2[:200]!r}")
            delivered[owner] = json.loads(b2).get("imported", {})
            entries += len(shard["index"]) + len(shard["cache"]) \
                + len(shard.get("sessions") or [])
        with self._lock:
            st.drained = True
            if self.elastic:
                survivors = [a for a in self.ring.replicas
                             if a != address]
                if survivors:
                    # Leave = drain (ISSUE 17): in elastic mode the
                    # drained replica leaves the ring itself — not just
                    # route-time exclusion — and the membership epoch
                    # advances so peer routers gossip the removal.
                    # Routing outcomes are unchanged (a drained member
                    # was already excluded on every walk).
                    self.ring = HashRing(survivors,
                                         vnodes=self.ring.vnodes)
                    self.epoch += 1
        self._c_drains.inc()
        self._c_handoff.inc(entries)
        telemetry.default_registry().event(
            "fault", fault="fleet_drain_handoff", replica=address,
            entries=entries, recipients=sorted(delivered))
        out = {"replica": address,
               "index_entries": len(snapshot["index"]),
               "cache_seeds": len(snapshot["cache"]),
               "handed_off": entries,
               "recipients": delivered}
        if "sessions" in snapshot:
            # Conditional like the snapshot section itself: drains of
            # session-free replicas keep the PR 15 response body.
            out["sessions"] = len(snapshot["sessions"])
        return out

    # ------------------------------------------------------------ metrics

    def render_metrics(self) -> str:
        lines = self.registry.render_lines()
        states = self.replica_states()
        lines.append("# HELP deppy_fleet_replica_up Replica breaker "
                     "verdict: 1 = routable, 0 = dead or drained.")
        lines.append("# TYPE deppy_fleet_replica_up gauge")
        for st in states:
            up = 0 if (st["dead"] or st["drained"]) else 1
            lines.append(
                f'deppy_fleet_replica_up{{replica="{st["replica"]}"}} '
                f"{up}")
        if self.elastic:
            # Gated so the static-mode page stays byte-identical to
            # PR 15 (the off-switch acceptance pin).
            lines.append("# HELP deppy_fleet_epoch Monotonic membership"
                         " epoch — increments on every committed "
                         "join/leave arc flip (and on gossip adoption "
                         "of a newer peer view).")
            lines.append("# TYPE deppy_fleet_epoch gauge")
            with self._lock:
                lines.append(f"deppy_fleet_epoch {self.epoch}")
        return "\n".join(lines) + "\n"


def _router_handler(router: Router):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        # ------------------------------------------------------ plumbing

        def _send(self, status: int, body: bytes,
                  ctype: str = "application/json",
                  extra: Optional[dict] = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, status: int, doc: dict) -> None:
            self._send(status, json.dumps(doc).encode())

        def _read_body(self) -> Optional[bytes]:
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = -1
            if length < 0 or length > router.max_body_bytes:
                self._send_json(413 if length > 0 else 400,
                                {"error": "invalid or oversized body"})
                return None
            return self.rfile.read(length)

        # traceparent naming the router hop span as parent (ISSUE 16);
        # set only while an aggregator is armed, so disarmed forwards
        # stay byte-identical.
        _hop_traceparent = None

        def _fwd_headers(self) -> dict:
            h = {k: self.headers[k] for k in FORWARD_HEADERS
                 if self.headers.get(k) is not None}
            if self._hop_traceparent:
                h["traceparent"] = self._hop_traceparent
            return h

        def _relay(self, status: int, body: bytes, hdrs: dict) -> None:
            self._send(status, body,
                       hdrs.get("Content-Type", "application/json"),
                       {k: hdrs[k] for k in ECHO_HEADERS if k in hdrs})

        def _forward_with_retry(self, key, path: str, body: bytes):
            """Route ``key``, forward, and on a TRANSPORT failure retry
            once on the ring successor (the replica that inherits the
            key's arc).  Returns the relayed (status, body, headers)
            plus the serving replica, or None after sending the
            no-replica 503."""
            headers = self._fwd_headers()
            target = router.target_for(key)
            tried: List[str] = []
            while target is not None:
                try:
                    out = router.forward(target, "POST", path, body,
                                         headers)
                except OSError:
                    tried.append(target)
                    if len(tried) > 1:
                        break
                    router._c_retries.inc()
                    target = router.target_for(key, exclude=tried)
                    continue
                return out + (target,)
            self._send_json(503, {
                "error": "fleet: no replica reachable",
                "retry_after_s": max(router.probe_interval_s, 1.0)})
            return None

        # ------------------------------------------------------ endpoints

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                self._send(200, router.render_metrics().encode(),
                           "text/plain; version=0.0.4")
            elif path == "/fleet/replicas":
                doc = {
                    "policy": router.policy,
                    "vnodes": router.ring.vnodes,
                    "replicas": router.replica_states()}
                if router.elastic:
                    # Appended after the PR 15 keys so the static-mode
                    # body stays byte-identical (the off-switch pin).
                    from .membership import membership_view

                    view = membership_view(router)
                    doc["membership"] = router.membership
                    doc["epoch"] = view["epoch"]
                    doc["members"] = view["members"]
                    doc["peers"] = router.peers
                self._send_json(200, doc)
            elif path == "/fleet/policy":
                self._policy()
            elif path == "/fleet/metrics":
                # Metrics federation (ISSUE 16): every live replica
                # scraped concurrently, families merged under the
                # `replica` label, fleet rollups on top.
                router._c_requests.inc(label="fleet_metrics")
                from ..obs import federate

                self._send(200,
                           federate.render_fleet_metrics(router).encode(),
                           "text/plain; version=0.0.4")
            elif path == "/fleet/status":
                router._c_requests.inc(label="fleet_status")
                agg = router.aggregator
                self._send_json(200, {
                    "policy": router.policy,
                    "vnodes": router.ring.vnodes,
                    "replicas": router.replica_states(),
                    "telemetry": {
                        "ingested": agg.counts() if agg else {}}})
            elif path == "/debug/traces":
                self._traces()
            else:
                self._send_json(404, {"error": "not found"})

        def _policy(self):
            """SLO-burn autoscale recommendation (ISSUE 17): scrape the
            fleet, run the policy, recommend.  Execution stays
            operator-driven — this endpoint never mutates membership."""
            if not router.elastic:
                self._send_json(404, {"error": "not found"})
                return
            router._c_requests.inc(label="policy")
            from .policy import evaluate

            self._send_json(200, {"policy": evaluate(router)})

        def _traces(self):
            """Cross-replica trace lookup (ISSUE 16): only the replica
            that served a request retains it in its flight recorder, so
            the query fans out and the first hit is relayed."""
            router._c_requests.inc(label="traces")
            last = None
            for address in router.live_replicas():
                try:
                    out = router.forward(address, "GET", self.path, None,
                                         timeout=PROBE_TIMEOUT_S * 5)
                except OSError:
                    continue
                if out[0] == 200:
                    self._relay(*out)
                    return
                last = out
            if last is not None:
                self._relay(*last)
            else:
                self._send_json(503, {
                    "error": "fleet: no replica reachable",
                    "retry_after_s": max(router.probe_interval_s, 1.0)})

        def do_POST(self):
            path = self.path.split("?", 1)[0]
            if path == "/v1/resolve":
                self._resolve()
            elif path in ("/v1/catalog/publish", "/v1/resolve/preview"):
                self._fan_out(path)
            elif path == "/v1/session" or path.startswith("/v1/session/"):
                self._session(path)
            elif path == "/fleet/drain":
                self._drain()
            elif path == "/fleet/join":
                self._join()
            elif path == "/fleet/sync":
                self._sync()
            elif path == "/fleet/telemetry":
                self._telemetry()
            elif path == "/debug/dump":
                router._c_requests.inc(label="dump")
                raw = self._read_body()
                if raw is None:
                    return
                self._send_json(200, router.dump_fanout(raw))
            else:
                self._send_json(404, {"error": "not found"})

        def _telemetry(self):
            """Replica-pushed telemetry batches (ISSUE 16).  404 with no
            aggregator armed — the streamer counts the rejection and
            drops the batch; serving is never in the loop."""
            if router.aggregator is None:
                self._send_json(404, {"error": "not found"})
                return
            router._c_requests.inc(label="telemetry")
            raw = self._read_body()
            if raw is None:
                return
            try:
                doc = json.loads(raw or b"null")
            except (ValueError, json.JSONDecodeError) as e:
                self._send_json(400,
                                {"error": f"invalid JSON body: {e}"})
                return
            accepted, err = router.aggregator.ingest(doc)
            if err is not None:
                self._send_json(400, {"error": err})
                return
            if accepted:
                # Route gossip (ISSUE 19) rides the same push: any
                # live-learned routing rows in this batch fan out to
                # the fleet off-thread — a replica's streamer flush
                # must never block on N peer round-trips.
                threading.Thread(
                    target=router.gossip_routes, args=(doc,),
                    name="route-gossip", daemon=True).start()
            self._send_json(200, {"accepted": accepted})

        def _resolve(self):
            router._c_requests.inc(label="resolve")
            raw = self._read_body()
            if raw is None:
                return
            if router.aggregator is not None:
                # Router hop span (ISSUE 16): adopt (or mint) the
                # request's trace, open router.forward on the router's
                # registry, and forward a traceparent naming the hop as
                # parent — each replica's service.request root nests
                # under it, so the merged sink reconstructs the routed
                # request as ONE span tree.
                ctx = telemetry.trace.context_from_headers(
                    self.headers.get("traceparent"),
                    self.headers.get("X-Deppy-Request-Id"))
                with telemetry.trace.activate(ctx), \
                        router.registry.span(
                            "router.forward", path="/v1/resolve",
                            request_id=ctx.request_id) as sp:
                    if sp.span_id:
                        self._hop_traceparent = (
                            f"00-{ctx.trace_id}-{sp.span_id}-01")
                    try:
                        self._resolve_routed(raw, sp)
                    finally:
                        self._hop_traceparent = None
                return
            self._resolve_routed(raw, None)

        def _resolve_routed(self, raw: bytes, sp) -> None:
            try:
                doc = json.loads(raw or b"null")
                keys = doc_affinity_keys(doc)
            except (ValueError, json.JSONDecodeError):
                # Unparseable bodies forward untouched: the replica
                # renders the same 400 a single server would, so the
                # router adds no second validation surface.
                keys = [None]
            by_target: Dict[Optional[str], List[int]] = {}
            for i, key in enumerate(keys):
                by_target.setdefault(
                    router.target_for(key), []).append(i)
            if sp is not None:
                sp.set(problems=len(keys), targets=len(by_target))
            if len(by_target) == 1:
                # One owner: forward the ORIGINAL bytes — byte-identity
                # with a single replica is structural, not re-rendered.
                out = self._forward_with_retry(keys[0], "/v1/resolve",
                                               raw)
                if out is None:
                    return
                status, body, hdrs, target = out
                if status == 200:
                    router._c_routed.inc(len(keys), label=target)
                if sp is not None:
                    sp.set(replica=target, status=status)
                self._relay(status, body, hdrs)
                return
            self._resolve_split(doc, keys, by_target)

        def _resolve_split(self, doc, keys, groups) -> None:
            """A batch spanning replicas: per-replica sub-batches
            (``groups``: the routing pass _resolve already computed —
            recomputing would double the ring walks, and in roundrobin
            mode re-advance the rotation off the assignment actually
            measured) forwarded concurrently, results merged back in
            input order.  Any non-200 sub-response wins (lowest
            problem index first — deterministic), mirroring the
            all-or-nothing semantics of a single server's
            request-level errors."""
            problems = doc["problems"]
            results: List[Optional[dict]] = [None] * len(problems)
            failures: List[tuple] = []
            lock = threading.Lock()

            def one(target: Optional[str], idxs: List[int]) -> None:
                sub = json.dumps(
                    {"problems": [problems[i] for i in idxs]}).encode()
                out = None
                if target is not None:
                    first = self._fwd_headers()
                    tried = [target]
                    while True:
                        try:
                            out = router.forward(target, "POST",
                                                 "/v1/resolve", sub,
                                                 first)
                            break
                        except OSError:
                            if len(tried) > 1:
                                out = None
                                break
                            router._c_retries.inc()
                            target = router.target_for(
                                keys[idxs[0]], exclude=tried)
                            if target is None:
                                break
                            tried.append(target)
                with lock:
                    if out is None:
                        failures.append((idxs[0], 503, json.dumps({
                            "error": "fleet: no replica reachable",
                            "retry_after_s": max(
                                router.probe_interval_s, 1.0),
                        }).encode(), {}))
                        return
                    status, body, hdrs = out
                    if status != 200:
                        failures.append((idxs[0], status, body, hdrs))
                        return
                    router._c_routed.inc(len(idxs), label=target)
                    for i, res in zip(idxs,
                                      json.loads(body)["results"]):
                        results[i] = res

            threads = [threading.Thread(target=one, args=(t, idxs),
                                        daemon=True)
                       for t, idxs in groups.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if failures:
                _, status, body, hdrs = min(failures)
                self._relay(status, body, hdrs)
                return
            self._send(200, json.dumps({"results": results}).encode())

        def _session(self, path: str) -> None:
            """Session tier routing (ISSUE 20).  ``POST /v1/session``
            routes by the catalog's family key — the same affinity walk
            as a one-problem ``/v1/resolve``, so the session lands on
            the replica already warm for that family.  Ops route by the
            session's family key from the ``X-Deppy-Session`` header
            (minted at create time, echoed by the client), so the hot
            path never re-encodes the catalog.  Transport failures
            retry once on the ring successor; an op whose retry lands
            on a replica that does not hold the session surfaces a
            clean 409 "session lost" — never a transport 502."""
            is_create = path == "/v1/session"
            router._c_requests.inc(
                label="session" if is_create else "session_op")
            raw = self._read_body()
            if raw is None:
                return
            if is_create:
                try:
                    keys = doc_affinity_keys(json.loads(raw or b"null"))
                except (ValueError, json.JSONDecodeError, KeyError,
                        TypeError):
                    # Unparseable/odd bodies forward untouched: the
                    # replica renders the same 400 a single server
                    # would.
                    keys = [None]
                key = keys[0] if keys else None
            else:
                key = self.headers.get("X-Deppy-Session") or None
            headers = self._fwd_headers()
            target = router.target_for(key)
            tried: List[str] = []
            out = None
            while target is not None:
                try:
                    out = router.forward(target, "POST", path, raw,
                                         headers)
                except OSError:
                    tried.append(target)
                    if len(tried) > 1:
                        out = None
                        break
                    router._c_retries.inc()
                    target = router.target_for(key, exclude=tried)
                    continue
                break
            if out is None:
                self._send_json(503, {
                    "error": "fleet: no replica reachable",
                    "retry_after_s": max(router.probe_interval_s, 1.0)})
                return
            status, body, hdrs = out
            if status == 404 and not is_create and tried:
                # The holding replica died mid-session and the ring
                # successor (which answered) has no such session: the
                # retained state is gone, not the transport.  Clients
                # see one unambiguous signal to re-create and replay.
                self._send_json(409, {"error": "session lost"})
                return
            if status == 200:
                router._c_routed.inc(label=target)
            self._relay(status, body, hdrs)

        def _fan_out(self, path: str) -> None:
            """Publish / preview fan-out to every live replica."""
            endpoint = ("publish" if path.endswith("publish")
                        else "preview")
            router._c_requests.inc(label=endpoint)
            raw = self._read_body()
            if raw is None:
                return
            headers = self._fwd_headers()
            live = router.live_replicas()
            if not live:
                self._send_json(503, {
                    "error": "fleet: no replica reachable",
                    "retry_after_s": max(router.probe_interval_s, 1.0)})
                return
            merged: Dict[str, float] = {}
            previews: List = []
            errors = 0
            first_error = None
            for address in live:
                try:
                    status, body, _ = router.forward(
                        address, "POST", path, raw, headers)
                except OSError:
                    errors += 1
                    continue
                router._c_fanout.inc()
                if status != 200:
                    errors += 1
                    if first_error is None:
                        first_error = (status, body)
                    continue
                payload = json.loads(body)
                if endpoint == "publish":
                    for k, v in (payload.get("publish") or {}).items():
                        if isinstance(v, (int, float)):
                            merged[k] = merged.get(k, 0) + v
                else:
                    previews.extend(payload.get("preview") or [])
            if errors == len(live):
                if first_error is not None:
                    # Every replica answered the same rejection (e.g. a
                    # malformed publish, or the tier off fleet-wide):
                    # relay it rather than masking as a router error.
                    self._send(first_error[0], first_error[1])
                else:
                    # Every forward failed at the TRANSPORT level (all
                    # replicas died between probe cycles): a 200 with
                    # zero recipients would read as "delta propagated"
                    # / "preview empty" when nothing was reached.
                    self._send_json(503, {
                        "error": "fleet: no replica reachable",
                        "retry_after_s": max(
                            router.probe_interval_s, 1.0)})
                return
            if endpoint == "publish":
                merged["replicas"] = len(live) - errors
                merged["errors"] = errors
                self._send_json(200, {"publish": merged})
            else:
                self._send_json(200, {"preview": previews})

        def _drain(self):
            router._c_requests.inc(label="drain")
            raw = self._read_body()
            if raw is None:
                return
            try:
                doc = json.loads(raw or b"null")
            except (ValueError, json.JSONDecodeError) as e:
                self._send_json(400,
                                {"error": f"invalid JSON body: {e}"})
                return
            if not isinstance(doc, dict) \
                    or not isinstance(doc.get("replica"), str):
                self._send_json(
                    400, {"error": 'drain requires {"replica": '
                          '"host:port"}'})
                return
            try:
                out = router.drain(doc["replica"])
            except ValueError as e:
                self._send_json(400, {"error": str(e)})
                return
            except (OSError, SnapshotFormatError, json.JSONDecodeError,
                    faults.InjectedFault) as e:
                # InjectedFault included (ISSUE 17 satellite): a
                # fault-plan-poisoned fleet.forward during the handoff
                # must surface as the same 502 a real transport failure
                # does — and the replica stays routable either way.
                self._send_json(502, {"error": f"drain failed: {e}"})
                return
            self._send_json(200, {"drain": out})

        def _join(self):
            """Runtime membership join (ISSUE 17 tentpole): stream the
            joiner its inherited warm state, then atomically flip its
            arcs live.  Any failure before the flip leaves membership
            exactly as it was — 502, joiner not admitted."""
            if not router.elastic:
                self._send_json(404, {"error": "not found"})
                return
            router._c_requests.inc(label="join")
            raw = self._read_body()
            if raw is None:
                return
            try:
                doc = json.loads(raw or b"null")
            except (ValueError, json.JSONDecodeError) as e:
                self._send_json(400,
                                {"error": f"invalid JSON body: {e}"})
                return
            if not isinstance(doc, dict) \
                    or not isinstance(doc.get("replica"), str):
                self._send_json(
                    400, {"error": 'join requires {"replica": '
                          '"host:port"}'})
                return
            from .membership import join_replica

            try:
                out = join_replica(router, doc["replica"])
            except ValueError as e:
                self._send_json(400, {"error": str(e)})
                return
            except (OSError, SnapshotFormatError, json.JSONDecodeError,
                    faults.InjectedFault) as e:
                self._send_json(502, {"error": f"join failed: {e}"})
                return
            self._send_json(200, {"join": out})

        def _sync(self):
            """Peer-router membership gossip (ISSUE 17): reconcile the
            sender's epoch-versioned view, answer with ours — one
            exchange converges both directions."""
            if not router.elastic:
                self._send_json(404, {"error": "not found"})
                return
            router._c_requests.inc(label="sync")
            raw = self._read_body()
            if raw is None:
                return
            from .membership import reconcile

            try:
                doc = json.loads(raw or b"null")
                view = doc.get("view") if isinstance(doc, dict) \
                    else None
                out = reconcile(router, view)
            except (ValueError, json.JSONDecodeError) as e:
                self._send_json(400,
                                {"error": f"invalid sync view: {e}"})
                return
            self._send_json(200, {"view": out})

    return Handler


def serve_router(bind_address: str = ":8079", replicas=None,
                 vnodes: Optional[int] = None,
                 probe_interval_s: Optional[float] = None,
                 probe_failures: Optional[int] = None,
                 policy: str = "affinity",
                 obs_sink: Optional[str] = None,
                 membership: Optional[str] = None,
                 peers=None) -> None:
    """Blocking entry point for ``deppy route`` — the router analog of
    ``service.serve`` (SIGTERM/Ctrl-C stop it cleanly)."""
    import signal
    import sys

    router = Router(bind_address=bind_address, replicas=replicas,
                    vnodes=vnodes, probe_interval_s=probe_interval_s,
                    probe_failures=probe_failures, policy=policy,
                    obs_sink=obs_sink, membership=membership,
                    peers=peers)
    router.start()
    stop = threading.Event()

    def _on_sigterm(signum, frame):
        stop.set()

    def _on_sigusr2(signum, frame):
        # Fleet-wide flight-recorder dump (ISSUE 16): the replica-local
        # SIGUSR2 semantics, fanned out — one signal on the router
        # flushes every live replica's recorder into its sink/stream.
        out = router.dump_fanout()
        total = sum(out["dumped"].values())
        print(f"[route] SIGUSR2: dumped {total} flight-recorder "
              f"trace(s) across {len(out['dumped'])} replica(s)"
              + (f"; unreachable: {','.join(out['errors'])}"
                 if out["errors"] else ""),
              file=sys.stderr, flush=True)

    prev = signal.signal(signal.SIGTERM, _on_sigterm)
    prev_usr2 = None
    if hasattr(signal, "SIGUSR2"):  # absent on Windows
        prev_usr2 = signal.signal(signal.SIGUSR2, _on_sigusr2)
    extra = ""
    if router.elastic:
        extra = ", membership elastic" + (
            f", {len(router.peers)} peer(s)" if router.peers else "")
    print(f"deppy fleet router listening on :{router.api_port} "
          f"({len(router.ring.replicas)} replicas, policy "
          f"{router.policy}{extra})", flush=True)
    try:
        while not stop.is_set():
            stop.wait(3600)
    except KeyboardInterrupt:
        pass
    finally:
        signal.signal(signal.SIGTERM, prev)
        if prev_usr2 is not None:
            signal.signal(signal.SIGUSR2, prev_usr2)
        router.shutdown()


# For the smoke/bench harnesses: how long a router takes to notice a
# dead replica (probe interval x failure threshold) — chaos assertions
# derive their settle windows from this instead of hardcoding sleeps.
def detection_window_s(router: Router) -> float:
    return router.probe_interval_s * router.probe_failures
