"""Warm-state snapshot / handoff serialization (ISSUE 15, piece 3).

A replica's warm tier is two in-process stores: the clause-set index
(zero-backtrack SAT models keyed by clause-set fingerprint — the
warm-start seeds) and the exact result cache.  A drain without handoff
throws both away and the inheriting replicas cold-solve every family
the drained replica owned; this module serializes them into one
versioned, integrity-checked JSON document:

  * **index entries** round-trip at full fidelity (per-row multiset,
    vocabulary, model, cold-equivalent steps) — an imported entry plans
    warm starts exactly like the original;
  * **exact-cache seeds** carry definitive SAT solution dicts only.
    UNSAT cores hold live constraint objects (not worth a codec for a
    rare, cheap-to-recompute case) and Incomplete entries are
    budget-relative; both re-solve cold once and re-enter the cache.
  * **sessions** (ISSUE 20, only when the replica runs the session
    tier): each live resolution session's retained problem, assumption
    stack with its test-scope structure, remaining lease, and private
    warm index — so interactive sessions survive elastic membership
    changes.  The section is OPTIONAL and only present when a session
    store was exported: snapshots from (and to) session-free builds
    stay byte-identical.

Every entry carries its family ``affinity`` key so the router can
split a draining replica's snapshot across the replicas inheriting its
ring arcs (:meth:`deppy_tpu.fleet.router.Router` ``POST /fleet/drain``).
The checksum is over the canonical JSON of the payload — a truncated
or bit-flipped handoff is rejected loudly (:class:`SnapshotFormatError`)
rather than silently poisoning the inheritor's warm tier.
"""

from __future__ import annotations

import hashlib
import json
from typing import Callable, Dict, List, Optional

from .ring import affinity_key

SNAPSHOT_VERSION = 1


class SnapshotFormatError(ValueError):
    """Malformed, version-skewed, or integrity-failed snapshot."""


def _checksum(payload: dict) -> str:
    canon = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def _seal(index_entries: List[dict], cache_seeds: List[dict],
          sessions: Optional[List[dict]] = None) -> dict:
    payload = {"version": SNAPSHOT_VERSION, "index": index_entries,
               "cache": cache_seeds}
    if sessions is not None:
        # Conditional key, checksummed when present: a session-free
        # export stays byte-identical to pre-session snapshots, and a
        # tampered sessions list fails verification like any other
        # section.
        payload["sessions"] = sessions
    return {**payload, "checksum": _checksum(payload)}


def index_entry_to_dict(entry) -> dict:
    """Serialize one clause-set-index entry (shared by the scheduler's
    shared index and each session's private index — ISSUE 20)."""
    return {
        "key": entry.key,
        "vocab_n": entry.vocab[0],
        "vocab_ids": list(entry.vocab[1]),
        "rows": [[list(k), n] for k, n in entry.rows.items()],
        "model": [int(b) for b in entry.model],
        "steps": entry.steps,
        "backtracks": entry.backtracks,
        "affinity": affinity_key(entry.vocab[1]),
    }


def import_index_entry(index, raw: dict) -> bool:
    """Deserialize + import one index entry; ``True`` when admitted
    (live state wins — a fresher local entry keeps its place).  Raises
    :class:`SnapshotFormatError` on a malformed entry."""
    import numpy as np

    try:
        from collections import Counter

        rows = Counter({tuple(k): int(n) for k, n in raw["rows"]})
        vocab = (int(raw["vocab_n"]),
                 tuple(str(i) for i in raw["vocab_ids"]))
        model = np.asarray(raw["model"], dtype=bool)
        return index.import_entry(
            str(raw["key"]), rows, vocab, model,
            int(raw["steps"]), int(raw["backtracks"]))
    except (KeyError, TypeError, ValueError) as e:
        raise SnapshotFormatError(
            f"malformed snapshot index entry: {e}") from e


def export_warm_state(scheduler, sessions=None) -> dict:
    """Serialize one scheduler's warm tier.  Works with either store
    absent (tier off): the corresponding section is just empty.  With a
    session store (ISSUE 20) the live sessions ride along; without one
    the document is byte-identical to the pre-session format."""
    index_entries: List[dict] = []
    index = getattr(scheduler, "incremental", None)
    if index is not None:
        for entry in index.export_entries():
            index_entries.append(index_entry_to_dict(entry))
    cache_seeds: List[dict] = []
    cache = getattr(scheduler, "cache", None)
    if cache is not None:
        for key, budget, solution in cache.export_seeds():
            cache_seeds.append({
                "key": key,
                "budget": budget,
                "solution": solution,
                "affinity": affinity_key(solution.keys()),
            })
    session_entries = None
    if sessions is not None:
        session_entries = sessions.export_entries()
    return _seal(index_entries, cache_seeds, sessions=session_entries)


def verify_snapshot(doc) -> dict:
    """Validate shape, version, and checksum; returns ``doc``."""
    if not isinstance(doc, dict):
        raise SnapshotFormatError(
            f"snapshot must be an object, got {type(doc).__name__}")
    if doc.get("version") != SNAPSHOT_VERSION:
        raise SnapshotFormatError(
            f"unsupported snapshot version {doc.get('version')!r} "
            f"(this build speaks {SNAPSHOT_VERSION})")
    if not isinstance(doc.get("index"), list) \
            or not isinstance(doc.get("cache"), list):
        raise SnapshotFormatError(
            'snapshot requires "index" and "cache" lists')
    payload = {"version": doc["version"], "index": doc["index"],
               "cache": doc["cache"]}
    if "sessions" in doc:
        if not isinstance(doc["sessions"], list):
            raise SnapshotFormatError('"sessions" must be a list')
        payload["sessions"] = doc["sessions"]
    if doc.get("checksum") != _checksum(payload):
        raise SnapshotFormatError(
            "snapshot integrity check failed (checksum mismatch)")
    return doc


def import_warm_state(scheduler, doc, sessions=None) -> dict:
    """Merge a verified snapshot into ``scheduler``'s warm tier.

    Live state wins: an index key already present keeps its (at least
    as fresh) local entry, and the exact cache's own supersede rules
    apply to seeds.  A ``sessions`` section imports into the given
    session store (live session ids win; entries are dropped without
    one — a session-free inheritor still takes the index/cache).
    Returns the merge accounting the endpoint renders; the session
    keys appear only when the document carried the section, so
    pre-session snapshot responses stay byte-identical."""
    verify_snapshot(doc)
    index = getattr(scheduler, "incremental", None)
    idx_in = idx_skip = 0
    for raw in doc["index"]:
        if index is None:
            break
        if import_index_entry(index, raw):
            idx_in += 1
        else:
            idx_skip += 1
    cache = getattr(scheduler, "cache", None)
    seeds = 0
    for raw in doc["cache"]:
        if cache is None:
            break
        try:
            sol = raw["solution"]
            if not isinstance(sol, dict):
                raise TypeError('"solution" must be an object')
            cache.store(str(raw["key"]), int(raw["budget"]),
                        {str(k): bool(v) for k, v in sol.items()})
        except (KeyError, TypeError, ValueError) as e:
            raise SnapshotFormatError(
                f"malformed snapshot cache seed: {e}") from e
        seeds += 1
    out = {"index_imported": idx_in, "index_skipped": idx_skip,
           "cache_seeds": seeds}
    if "sessions" in doc:
        ses_in = ses_skip = 0
        for raw in doc["sessions"]:
            if sessions is None:
                ses_skip += 1
                continue
            if sessions.import_entry(raw):
                ses_in += 1
            else:
                ses_skip += 1
        out["sessions_imported"] = ses_in
        out["sessions_skipped"] = ses_skip
    return out


def split_snapshot(doc, assign: Callable[[str], Optional[str]]
                   ) -> Dict[str, dict]:
    """Partition a verified snapshot by each entry's family owner
    (``assign(affinity) -> replica-or-None``); each shard is re-sealed
    so recipients verify integrity end to end.  Entries assigned None
    (no surviving owner) are dropped."""
    verify_snapshot(doc)
    sections = ("index", "cache") + (("sessions",)
                                     if "sessions" in doc else ())
    shards: Dict[str, Dict[str, List[dict]]] = {}
    for section in sections:
        for entry in doc[section]:
            owner = assign(entry.get("affinity"))
            if owner is None:
                continue
            shard = shards.setdefault(
                owner, {s: [] for s in sections})
            shard[section].append(entry)
    return {owner: _seal(s["index"], s["cache"],
                         sessions=s.get("sessions"))
            for owner, s in shards.items()}
