"""deppy_tpu.profile — engine cost profiler + per-tenant SLO accounting
(ISSUE 11 tentpole).

Every remaining ROADMAP lever is gated on measurement this package
collects continuously instead of by hand:

  * **ledger** — the per-dispatch trip ledger: lockstep while-trip
    counts vs per-lane useful work, straggler distribution (p50/p99
    lane work vs batch trips), pad/fill waste per size class, and
    per-backend cost attribution.  Sampled at a registry-declared rate
    (``DEPPY_TPU_PROFILE`` / ``DEPPY_TPU_PROFILE_SAMPLE``) so the armed
    overhead is bounded; disarmed, the whole subsystem is one cached
    bool check per dispatch and emits nothing.  Sampled dispatches emit
    ``profile`` events into the PR 1 JSONL sink (stamped onto the
    active PR 4 trace), update the ``deppy_profile_*`` metric families,
    and fill the :class:`~deppy_tpu.telemetry.SolveReport` ledger
    fields the bench economics columns read.
  * **slo** — per-tenant SLO accounting: tenant identity from the
    ``X-Deppy-Tenant`` header threaded through scheduler groups, a
    declarative SLO config (``DEPPY_TPU_SLO``: target p99 + error
    budget per tenant), per-tenant request/latency/deadline-miss
    counters, and burn-rate gauges on ``/metrics`` + ``/debug/slo``.
  * **report** — the ``deppy profile`` CLI: reads the sink and renders
    the cost model the A/B history computed by hand — trip-overhead
    regression, useful-work ratio per size class, straggler/pad waste
    breakdowns, per-backend µs/solve.  This report is the baseline
    artifact the watched-literal kernel rewrite (ROADMAP item 1) must
    beat.

See docs/observability.md (Profiling / SLO accounting) for the event
schema, metric tables, and sampling semantics.
"""

from .ledger import (
    DEFAULT_TENANT,
    PROFILE_FAMILIES,
    armed,
    configure,
    dispatch_t0,
    override,
    record_backend_flush,
    record_device_dispatch,
    render_metric_lines,
    sample_rate,
)
from .slo import (SLOAccountant, SLOConfig, sanitize_replica,
                  sanitize_tenant, slo_config_from_env)

__all__ = [
    "DEFAULT_TENANT",
    "PROFILE_FAMILIES",
    "SLOAccountant",
    "SLOConfig",
    "render_metric_lines",
    "armed",
    "configure",
    "dispatch_t0",
    "override",
    "record_backend_flush",
    "record_device_dispatch",
    "sample_rate",
    "sanitize_replica",
    "sanitize_tenant",
    "slo_config_from_env",
]
