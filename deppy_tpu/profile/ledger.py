"""The per-dispatch trip ledger (ISSUE 11 tentpole, engine side).

The trip-overhead model that justifies the watched-literal kernel
rewrite (ROADMAP item 1: ~175µs per lockstep while-trip for ~10µs of
useful work) lived only in hand-run A/B narrative.  This module makes
the quantities behind it continuously measured:

  * **trips** — lockstep while-trip count per dispatched chunk: under
    ``vmap`` every lane pays the slowest lane's iteration count, so a
    chunk's trips are ``max(lane steps)``;
  * **lane work** — the per-lane useful iteration counts the engine
    already reports (``SolveResult.steps``), summed over live lanes;
  * **straggler distribution** — p50/p99 lane work vs batch trips, so
    whole-batch waste attributable to the slowest lane is a number;
  * **pad/fill waste** — the driver's existing fill ratios, attributed
    per dispatch and per size class;
  * **backend attribution** — device / host / hostpool / warm wall
    clock and lane counts, so portfolio racing (ROADMAP item 2) has
    measured per-backend cost curves to route by.

Arming and sampling are registry-declared (``DEPPY_TPU_PROFILE``,
``DEPPY_TPU_PROFILE_SAMPLE``, with ``--profile`` / ``--profile-sample``
CLI mirrors).  Disarmed (the default), :func:`dispatch_t0` is one
cached bool check per dispatch, no event is ever emitted, and no metric
family is registered — the pipeline is byte-identical to the
pre-profiler tree.  Armed, each sampled dispatch costs a few numpy
reductions over ≤ MAX_LANES-length step arrays plus one sink event —
measured ≤5% on ``bench.py --workload churn`` (acceptance bound).

Trace purity: every ledger read happens AFTER ``jax.device_get``
fetched the dispatch's results to host numpy — nothing here runs (or
synchronizes) inside traced code.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from typing import Optional

import numpy as np

from .. import config, telemetry

# The tenant every request without an X-Deppy-Tenant header accounts
# under (slo.py reads it too; declared here so ledger stays the leaf).
DEFAULT_TENANT = "default"

# Arming modes: "off" (default — zero events, zero families), "on".
# Sample rate in (0, 1]: fraction of dispatches profiled when armed
# (deterministic 1-in-round(1/rate) counter, not random, so tests and
# overhead bounds are reproducible).
DEFAULT_SAMPLE = 1.0

_LOCK = threading.Lock()
_ARMED: Optional[bool] = None          # None = resolve from env lazily
_INTERVAL: Optional[int] = None        # every Nth dispatch is sampled
# One counter PER CALL SITE (device / warm / host / hostpool): a single
# shared modulo counter phase-locks under periodic call patterns — an
# incremental-serving loop alternating warm-flush and device-dispatch
# gates would, at interval 2, sample only one of the two forever.
_COUNTERS: dict = {}


def _resolve_locked() -> None:
    """Fill whichever of the two settings is still unresolved from the
    environment — independently, so an explicit ``configure(mode=...)``
    with no explicit sample still gets the env/default interval (and
    vice versa)."""
    global _ARMED, _INTERVAL
    if _ARMED is None:
        raw = (config.env_raw("DEPPY_TPU_PROFILE", "off") or "off")
        _ARMED = raw.strip().lower() in ("on", "1", "true", "yes")
    if _INTERVAL is None:
        _INTERVAL = _interval_of(_env_sample())


def _env_sample() -> float:
    raw = config.env_raw("DEPPY_TPU_PROFILE_SAMPLE")
    if raw is None or not raw.strip():
        return DEFAULT_SAMPLE
    try:
        return float(raw)
    except ValueError:
        return DEFAULT_SAMPLE


def _interval_of(rate: float) -> int:
    if not (rate > 0):
        return 0  # 0/negative rate: armed but sampling nothing
    return max(int(round(1.0 / min(rate, 1.0))), 1)


def configure(mode: Optional[str] = None,
              sample: Optional[float] = None) -> None:
    """Install explicit profiler settings (the serve CLI's ``--profile``
    / ``--profile-sample`` mirrors).  ``None`` leaves that axis to env
    resolution (re-resolved on next use)."""
    global _ARMED, _INTERVAL
    with _LOCK:
        if mode is None:
            _ARMED = None
        else:
            _ARMED = str(mode).strip().lower() in ("on", "1", "true",
                                                   "yes")
        _INTERVAL = None if sample is None else _interval_of(float(sample))
        if _ARMED is None or _INTERVAL is None:
            _resolve_locked()


def armed() -> bool:
    """Fast check: is the profiler collecting at all?"""
    if _ARMED is None:
        with _LOCK:
            _resolve_locked()
    return bool(_ARMED)


def sample_rate() -> float:
    """The effective sampling rate (0.0 when sampling is disabled)."""
    if _ARMED is None:
        with _LOCK:
            _resolve_locked()
    return 0.0 if not _INTERVAL else 1.0 / _INTERVAL


@contextmanager
def override(mode: str, sample: float = 1.0):
    """Scoped arming (tests, the bench harness's ledger dispatch):
    restores the previous resolution state on exit."""
    global _ARMED, _INTERVAL
    with _LOCK:
        _resolve_locked()
        prev = (_ARMED, _INTERVAL)
        _ARMED = str(mode).strip().lower() in ("on", "1", "true", "yes")
        _INTERVAL = _interval_of(float(sample))
    try:
        yield
    finally:
        with _LOCK:
            _ARMED, _INTERVAL = prev


def dispatch_t0(site: str = "device") -> Optional[float]:
    """Sampling gate, called once at the top of each dispatch impl:
    returns a ``perf_counter`` start time when THIS dispatch is
    sampled, else None.  ``site`` names the caller's backend class —
    each site gets its own deterministic 1-in-N counter, so sampling
    at one site never phase-locks against another's call cadence.
    Disarmed this is one cached bool check — the driver's per-batch
    fast path stays flat."""
    if not armed() or not _INTERVAL:
        return None
    counter = _COUNTERS.get(site)
    if counter is None:
        with _LOCK:
            counter = _COUNTERS.setdefault(site, itertools.count())
    if next(counter) % _INTERVAL:
        return None
    return time.perf_counter()


# ---------------------------------------------------------------- recording


def _percentile(sorted_vals: np.ndarray, q: float) -> int:
    """Nearest-rank percentile over a pre-sorted int array — the
    shared telemetry statistic, cast back to a Python int."""
    return int(telemetry.percentile(sorted_vals, q))


def record_device_dispatch(t0: float, *, steps: np.ndarray, live: int,
                           chunk: int, size_class: int, pad_cells: int,
                           live_cells: int, backend: str = "device",
                           size_class_name: Optional[str] = None) -> None:
    """Record one sampled device dispatch's trip ledger.

    ``steps`` is the dispatch's final per-lane iteration counts
    (host numpy, length = padded lane total), live lanes first —
    exactly what the impls fetched; ``chunk`` is the lockstep program
    width (lanes per while-loop), so per-chunk trips are the max lane
    count within each chunk.  Updates the thread's active
    :class:`SolveReport` ledger fields, the ``deppy_profile_*``
    families on the default registry, and emits one ``profile`` event
    (stamped onto the active trace when one exists)."""
    dur_s = time.perf_counter() - t0
    total = int(steps.shape[0])
    live = min(int(live), total)
    chunk = max(int(chunk), 1)
    steps64 = steps.astype(np.int64, copy=False)
    trips = 0
    trip_slots = 0
    p99_trips = 0
    for lo in range(0, total, chunk):
        sl = steps64[lo: lo + chunk]
        live_sl = sl[: max(min(live - lo, chunk), 0)]
        if live_sl.size == 0:
            continue  # an all-pad chunk never dispatches
        t = int(sl.max())
        trips += t
        trip_slots += t * int(sl.shape[0])
        p99_trips += _percentile(np.sort(live_sl), 99)
    live_steps = steps64[:live]
    lane_work = int(live_steps.sum())
    s = np.sort(live_steps)
    p50 = _percentile(s, 50)
    p99 = _percentile(s, 99)
    useful = lane_work / trip_slots if trip_slots else 0.0
    straggler = p99_trips / trips if trips else 0.0
    pad_waste = 1.0 - live_cells / pad_cells if pad_cells else 0.0

    rep = telemetry.current_report()
    if rep is not None:
        rep.record_ledger(trips=trips, trip_slots=trip_slots,
                          lane_steps=lane_work, p99_trips=p99_trips)
    reg = telemetry.default_registry()
    reg.counter("deppy_profile_dispatches_total",
                "Sampled dispatches recorded by the trip ledger.").inc()
    reg.counter("deppy_profile_trips_total",
                "Lockstep while-trips paid by sampled dispatches "
                "(max lane steps per chunk, summed).").inc(trips)
    reg.counter("deppy_profile_lane_steps_total",
                "Useful per-lane engine iterations in sampled "
                "dispatches.").inc(lane_work)
    reg.histogram(
        "deppy_profile_useful_work_ratio",
        "Useful lane steps / lockstep trip-lane slots per sampled "
        "dispatch (low = trips wasted on padding and stragglers).",
        buckets=telemetry.RATIO_BUCKETS).observe(useful)
    reg.histogram(
        "deppy_profile_straggler_p99_ratio",
        "p99 lane work / batch trips per sampled dispatch (low = one "
        "straggler lane drives the whole batch's trip count).",
        buckets=telemetry.RATIO_BUCKETS).observe(straggler)
    reg.histogram(
        "deppy_profile_pad_waste_ratio",
        "Padded clause-cell waste per sampled dispatch.",
        buckets=telemetry.RATIO_BUCKETS).observe(pad_waste)
    _backend_counters(reg, backend, dur_s, live)
    fields = {}
    if size_class_name is not None:
        # The dispatch's ladder class (deppy_tpu.size_classes): keys
        # the `deppy profile` per-class table by name instead of the
        # raw bucketed cost.
        fields["size_class_name"] = size_class_name
    reg.event("profile", backend=backend, size_class=int(size_class),
              **fields,
              lanes=total, live=live, chunk=chunk, trips=trips,
              lane_steps=lane_work, lane_p50=p50, lane_p99=p99,
              useful_work_ratio=round(useful, 4),
              straggler_p99_ratio=round(straggler, 4),
              pad_waste_ratio=round(pad_waste, 4),
              pad_cells=int(pad_cells), live_cells=int(live_cells),
              solve_s=round(dur_s, 6))


def record_backend_flush(backend: str, lanes: int, lane_steps: int,
                         dur_s: float,
                         tenant: Optional[str] = None) -> None:
    """Cost attribution for a non-lockstep flush (host / hostpool /
    warm): wall clock and lane count per backend, plus one ``profile``
    event — no trip fields (there is no lockstep program to waste
    trips on).  Callers gate on :func:`dispatch_t0` so sampling and
    arming semantics match the device ledger.  ``tenant``: set only
    when every lane in the flush belongs to one tenant (the scheduler
    knows) — `deppy stats --tenant` then attributes the event; a
    mixed-tenant flush stays unstamped rather than misattributed."""
    reg = telemetry.default_registry()
    _backend_counters(reg, backend, dur_s, lanes)
    fields = {"backend": backend, "lanes": int(lanes),
              "live": int(lanes), "lane_steps": int(lane_steps),
              "solve_s": round(dur_s, 6)}
    if tenant is not None:
        fields["tenant"] = tenant
    reg.event("profile", **fields)


# Family order for the service-scrape mirror (render_metric_lines):
# matches registration order so /metrics diffs stay stable.
PROFILE_FAMILIES = (
    "deppy_profile_dispatches_total",
    "deppy_profile_trips_total",
    "deppy_profile_lane_steps_total",
    "deppy_profile_useful_work_ratio",
    "deppy_profile_straggler_p99_ratio",
    "deppy_profile_pad_waste_ratio",
    "deppy_profile_backend_seconds_total",
    "deppy_profile_backend_lanes_total",
)


def render_metric_lines() -> list:
    """Exposition lines for the profiler families, mirrored into the
    service's ``/metrics`` scrape (the faults/hostpool injection
    pattern): the families live on the pipeline-global default
    registry — where the driver records — and are absent until the
    first sampled dispatch, so a disarmed service's scrape is
    unchanged."""
    return telemetry.default_registry().render_families(PROFILE_FAMILIES)


def _backend_counters(reg, backend: str, dur_s: float, lanes: int) -> None:
    reg.counter(
        "deppy_profile_backend_seconds_total",
        "Wall-clock seconds of sampled solve work, by backend "
        "(device / host / hostpool / warm).",
        labelname="backend", initial=0.0).inc(dur_s, label=backend)
    reg.counter(
        "deppy_profile_backend_lanes_total",
        "Lanes solved in sampled dispatches, by backend.",
        labelname="backend").inc(lanes, label=backend)
