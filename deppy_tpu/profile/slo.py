"""Per-tenant SLO accounting (ISSUE 11 tentpole, serving side).

Tenant identity comes from the ``X-Deppy-Tenant`` request header
(default tenant otherwise), threaded through the scheduler's groups so
deadline expiries are attributable to the tenant whose lane expired,
not its coalesced batchmates.  The :class:`SLOAccountant` keeps one
bounded sliding window of request latencies per tenant and renders:

  * ``deppy_tenant_requests_total{tenant=}`` — requests served;
  * ``deppy_tenant_deadline_miss_total{tenant=}`` — requests with at
    least one deadline-degraded lane;
  * ``deppy_tenant_slo_violations_total{tenant=}`` — requests that
    violated the tenant's SLO (latency above target p99, a deadline
    miss, or a server error);
  * ``deppy_tenant_p99_seconds{tenant=}`` — p99 latency over the
    window;
  * ``deppy_tenant_burn_rate{tenant=}`` — (violating fraction of the
    window) / error budget: 1.0 = consuming the budget exactly, above
    1.0 = burning faster than the SLO allows.

The SLO itself is declarative (``DEPPY_TPU_SLO`` / ``--slo``): inline
JSON, ``@FILE``, or a file path — same spec convention as fault plans —
mapping tenant name to ``{"target_p99_s": ..., "error_budget": ...}``;
the ``"default"`` entry covers unlisted tenants.  Accounting is always
on in the service (a deque append and a few adds per request); only the
*rendered* families depend on traffic, so a tenant-free deployment's
``/metrics`` is unchanged until the first request lands.
"""

from __future__ import annotations

import json
import re
from collections import deque
from typing import Dict, Optional

# Built-in default SLO when no spec (or no "default" entry) is given:
# generous enough that an unconfigured service never alarms, tight
# enough that burn rate still moves under real degradation.
DEFAULT_TARGET_P99_S = 1.0
DEFAULT_ERROR_BUDGET = 0.01
# Sliding-window size per tenant (requests).  Burn rate and p99 are
# computed over this window, so they recover once the incident ends.
WINDOW = 256
# Distinct tenants tracked.  X-Deppy-Tenant is unauthenticated, so a
# client minting a fresh tenant per request must not grow server
# memory or /metrics cardinality without bound: past the cap, new
# names account under one shared overflow bucket (the cap is far above
# any real tenant population; a legit tenant seen before the flood
# keeps its own stats).
MAX_TENANTS = 256
OVERFLOW_TENANT = "_overflow"

# Tenant names become Prometheus label values: restrict to a safe
# charset so a hostile header can never inject exposition syntax.
_TENANT_RE = re.compile(r"[^A-Za-z0-9._-]+")
_MAX_TENANT_LEN = 64
# Replica identities are operator-set (never attacker-controlled), but
# they still land in label values — same charset plus ':' and '[]' so
# the conventional host:port spelling survives (ISSUE 15).
_REPLICA_RE = re.compile(r"[^A-Za-z0-9._:\[\]-]+")


def sanitize_replica(raw: Optional[str]) -> Optional[str]:
    """Serving-identity string → label-safe replica id (None when it
    sanitizes to nothing)."""
    if not raw:
        return None
    return _REPLICA_RE.sub("", raw.strip())[:_MAX_TENANT_LEN] or None


def sanitize_tenant(raw: Optional[str]) -> str:
    """Header value → tenant id: strip, drop unsafe characters, bound
    the length, and strip leading underscores (``_``-prefixed names —
    notably the ``_overflow`` cardinality bucket — are reserved for
    the accountant itself; an unauthenticated client must not be able
    to write into them); anything that sanitizes to nothing is the
    default tenant."""
    from .ledger import DEFAULT_TENANT

    if not raw:
        return DEFAULT_TENANT
    clean = _TENANT_RE.sub("", raw.strip()).lstrip("_")[:_MAX_TENANT_LEN]
    return clean or DEFAULT_TENANT


class SLOConfig:
    """Declarative per-tenant SLO targets."""

    def __init__(self, tenants: Optional[Dict[str, dict]] = None):
        self.tenants: Dict[str, dict] = {}
        for name, spec in (tenants or {}).items():
            if not isinstance(spec, dict):
                raise ValueError(
                    f"SLO entry for {name!r} must be an object, got "
                    f"{type(spec).__name__}")
            self.tenants[str(name)] = {
                "target_p99_s": float(
                    spec.get("target_p99_s", DEFAULT_TARGET_P99_S)),
                "error_budget": float(
                    spec.get("error_budget", DEFAULT_ERROR_BUDGET)),
            }

    def for_tenant(self, tenant: str) -> dict:
        return self.tenants.get(tenant) or self.tenants.get("default") or {
            "target_p99_s": DEFAULT_TARGET_P99_S,
            "error_budget": DEFAULT_ERROR_BUDGET,
        }

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> "SLOConfig":
        """Inline JSON, ``@FILE``, or a file path (the fault-plan spec
        convention).  Raises ``ValueError``/``OSError`` on a malformed
        spec — an operator SLO that silently parses to nothing would
        report every tenant green."""
        if not spec:
            return cls()
        text = spec.strip()
        if text.startswith("@"):
            with open(text[1:], "r", encoding="utf-8") as fh:
                text = fh.read()
        elif not text.startswith(("{", "[")):
            with open(text, "r", encoding="utf-8") as fh:
                text = fh.read()
        doc = json.loads(text)
        if not isinstance(doc, dict):
            raise ValueError(
                f"SLO spec must be a tenant->target mapping, got "
                f"{type(doc).__name__}")
        return cls(doc)


def slo_config_from_env() -> SLOConfig:
    from .. import config

    return SLOConfig.from_spec(config.env_raw("DEPPY_TPU_SLO"))


class _TenantStats:
    __slots__ = ("requests", "deadline_misses", "violations", "window")

    def __init__(self):
        self.requests = 0
        self.deadline_misses = 0
        self.violations = 0
        # (latency_s, violated) per request, bounded.
        self.window: deque = deque(maxlen=WINDOW)


class SLOAccountant:
    """Per-tenant request accounting + burn-rate rendering.

    Self-contained (own lock, own families) and appended to the
    service's ``/metrics`` scrape via :meth:`render_metric_lines` —
    the same injection pattern the fault and hostpool families use, so
    embedded servers and tests get it without touching a registry."""

    def __init__(self, config: Optional[SLOConfig] = None,
                 replica: Optional[str] = None):
        from ..analysis import lockdep

        self.config = config if config is not None else SLOConfig()
        # Replica identity (ISSUE 15): set from the server's serving
        # identity (--replica / DEPPY_TPU_REPLICA) so fleet burn rate
        # is attributable per tenant PER REPLICA when N replicas'
        # scrapes aggregate.  None (single-process deployments) keeps
        # the historical tenant-only label set byte for byte.
        self.replica = sanitize_replica(replica)
        self._lock = lockdep.make_lock("profile.slo")
        self._tenants: Dict[str, _TenantStats] = {}

    def observe(self, tenant: str, total_s: float,
                deadline_miss: bool = False, error: bool = False) -> None:
        """Account one finished request for ``tenant``."""
        slo = self.config.for_tenant(tenant)
        violated = bool(deadline_miss or error
                        or total_s > slo["target_p99_s"])
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                if len(self._tenants) >= MAX_TENANTS:
                    # Cardinality bound (unauthenticated header): new
                    # names past the cap share the overflow bucket.
                    tenant = OVERFLOW_TENANT
                    st = self._tenants.get(tenant)
                if st is None:
                    st = self._tenants[tenant] = _TenantStats()
            st.requests += 1
            if deadline_miss:
                st.deadline_misses += 1
            if violated:
                st.violations += 1
            st.window.append((float(total_s), violated))

    # ------------------------------------------------------------- reading

    def _tenant_view_locked(self, tenant: str, st: _TenantStats) -> dict:
        from ..telemetry import percentile

        slo = self.config.for_tenant(tenant)
        lat = sorted(l for l, _ in st.window)
        n = len(lat)
        p99 = float(percentile(lat, 99)) if n else 0.0
        bad = sum(1 for _, v in st.window if v)
        frac = bad / n if n else 0.0
        budget = max(slo["error_budget"], 1e-9)
        return {
            "requests": st.requests,
            "deadline_misses": st.deadline_misses,
            "violations": st.violations,
            "window": n,
            "window_violations": bad,
            "p99_s": round(p99, 6),
            "target_p99_s": slo["target_p99_s"],
            "error_budget": slo["error_budget"],
            "burn_rate": round(frac / budget, 4),
        }

    def snapshot(self) -> Dict[str, dict]:
        """The ``/debug/slo`` document body: every observed tenant's
        counters, window p99, SLO targets, and burn rate."""
        with self._lock:
            return {t: self._tenant_view_locked(t, st)
                    for t, st in sorted(self._tenants.items())}

    def render_metric_lines(self) -> list:
        """Prometheus exposition lines for every observed tenant, in
        sorted tenant order (deterministic scrapes, like the registry
        families)."""
        snap = self.snapshot()
        if not snap:
            return []
        lines = []
        rep = (f',replica="{self.replica}"' if self.replica else "")

        def fam(name, kind, help, value_of):
            lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {kind}")
            for tenant, view in snap.items():
                lines.append(
                    f'{name}{{tenant="{tenant}"{rep}}} '
                    f"{value_of(view)}")

        fam("deppy_tenant_requests_total", "counter",
            "Requests served, by tenant (X-Deppy-Tenant).",
            lambda v: v["requests"])
        fam("deppy_tenant_deadline_miss_total", "counter",
            "Requests with at least one deadline-degraded lane, by "
            "tenant.", lambda v: v["deadline_misses"])
        fam("deppy_tenant_slo_violations_total", "counter",
            "Requests violating the tenant's SLO (latency > target "
            "p99, deadline miss, or server error).",
            lambda v: v["violations"])
        fam("deppy_tenant_p99_seconds", "gauge",
            "p99 request latency over the tenant's sliding window.",
            lambda v: v["p99_s"])
        fam("deppy_tenant_burn_rate", "gauge",
            "Error-budget burn rate over the sliding window (1.0 = "
            "consuming the budget exactly).", lambda v: v["burn_rate"])
        return lines
