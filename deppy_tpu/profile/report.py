"""Offline cost-model rendering for ``deppy profile`` (ISSUE 11).

Reads a telemetry JSONL sink and reproduces, from ``profile`` events
alone, the cost model the A/B history computed by hand:

  * **trip-overhead regression** — least-squares fit of dispatch wall
    clock against lockstep trip count across sampled device
    dispatches: the slope is µs per while-trip (the ~175µs/trip figure
    of ROADMAP item 1), the intercept the per-dispatch fixed cost
    (pad/pack + upload + launch), and slope × mean useful-work ratio
    estimates the useful µs bought per trip;
  * **useful-work ratio per size class** — how much of each class's
    lockstep lane-step slots carried live work;
  * **straggler and pad waste breakdowns** per size class;
  * **per-backend µs/solve** — device / host / hostpool / warm cost
    attribution.

The rendered report is the baseline artifact the watched-literal
kernel rewrite (PR 12) must beat.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def summarize(path) -> dict:
    """Aggregate a sink's ``profile`` events into the cost model, and
    its ``race`` events (ISSUE 13) into the per-class portfolio table
    — wins/cancels/win-margin per backend plus straggler-resubmission
    counts, from the sink alone.  ``path`` is one sink path, or a list
    of per-replica sinks to merge (ISSUE 16: flight-recorder dump
    copies dedupe by their per-process event seq)."""
    from ..telemetry import iter_merged_sink_events, iter_sink_events

    events = (iter_sink_events(path) if isinstance(path, str)
              else iter_merged_sink_events(path))
    device: List[dict] = []
    backends: Dict[str, dict] = {}
    races: Dict[str, dict] = {}
    optimize: Dict[str, dict] = {}
    n_events = 0
    for ev in events:
        if ev is None:
            continue
        if ev.get("kind") == "race":
            _take_race(races, ev)
            continue
        if ev.get("kind") == "optimize":
            _take_optimize(optimize, ev)
            continue
        if ev.get("kind") != "profile":
            continue
        n_events += 1
        backend = str(ev.get("backend", "?"))
        agg = backends.setdefault(
            backend, {"events": 0, "lanes": 0, "solve_s": 0.0})
        agg["events"] += 1
        agg["lanes"] += int(ev.get("live", ev.get("lanes", 0)) or 0)
        try:
            agg["solve_s"] += float(ev.get("solve_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            pass
        if "trips" in ev:
            device.append(ev)
    for agg in backends.values():
        agg["solve_s"] = round(agg["solve_s"], 6)
        agg["us_per_solve"] = (
            round(agg["solve_s"] * 1e6 / agg["lanes"], 2)
            if agg["lanes"] else 0.0)
    for agg in races.values():
        margins = agg.pop("_margins")
        agg["win_margin_s_mean"] = (
            round(sum(margins) / len(margins), 6) if margins else None)
        agg["win_margin_s_min"] = (round(min(margins), 6)
                                   if margins else None)
        # Censored-aware per-backend speed (ISSUE 19): only walls from
        # entrants that actually finished feed the estimate — a
        # cancelled loser's partial wall measures when the cancel
        # landed, not how fast the backend solves.
        lane_us = agg.pop("_lane_us")
        agg["backend_us_per_lane"] = {
            b: {"us_per_lane": round(sum(vals) / len(vals), 2),
                "samples": len(vals)}
            for b, vals in sorted(lane_us.items())}
    for agg in optimize.values():
        agg["probe_s"] = round(agg["probe_s"], 6)
        agg["improvement_mean"] = (
            round(agg["improvement_total"] / agg["improvements"], 2)
            if agg["improvements"] else None)
    return {
        "profile_events": n_events,
        "device_dispatches": len(device),
        "trip_overhead": _trip_regression(device),
        "size_classes": _size_classes(device),
        "backends": backends,
        "races": races,
        "optimize": optimize,
    }


def _take_race(races: Dict[str, dict], ev: dict) -> None:
    key = str(ev.get("size_class_name", "?"))
    agg = races.setdefault(key, {
        "races": 0, "starts": {}, "wins": {}, "cancels": {},
        "resubmitted": 0, "no_winner": 0, "checked": 0,
        "check_mismatches": 0, "censored": {}, "_margins": [],
        "_lane_us": {},
    })
    if ev.get("resubmitted") is not None:
        agg["resubmitted"] += int(ev.get("resubmitted") or 0)
        return
    agg["races"] += 1
    for name in ev.get("entrants") or []:
        agg["starts"][name] = agg["starts"].get(name, 0) + 1
    winner = ev.get("winner")
    if winner is None:
        agg["no_winner"] += 1
    else:
        agg["wins"][winner] = agg["wins"].get(winner, 0) + 1
    for name in ev.get("cancelled") or []:
        agg["cancels"][name] = agg["cancels"].get(name, 0) + 1
    if ev.get("checked") is not None:
        agg["checked"] += 1
        if ev.get("checked") == "mismatch":
            agg["check_mismatches"] += 1
    m = ev.get("win_margin_s")
    if isinstance(m, (int, float)):
        agg["_margins"].append(float(m))
    lanes = max(int(ev.get("lanes") or 1), 1)
    wall = ev.get("wall_s")
    if winner is not None and isinstance(wall, (int, float)):
        agg["_lane_us"].setdefault(str(winner), []).append(
            1e6 * float(wall) / lanes)
    for loser in ev.get("losers") or []:
        if not isinstance(loser, dict):
            continue
        b = loser.get("backend")
        lw = loser.get("wall_s")
        if not isinstance(b, str):
            continue
        if loser.get("censored") or not isinstance(lw, (int, float)):
            agg["censored"][b] = agg["censored"].get(b, 0) + 1
            continue
        agg["_lane_us"].setdefault(b, []).append(1e6 * float(lw) / lanes)


def _take_optimize(optimize: Dict[str, dict], ev: dict) -> None:
    """One bound-tightening probe (ISSUE 18), keyed by probe mode —
    the warm-vs-cold split is the table's point: per-iteration rate,
    hit ratio, and which backend wins the cold probes, from the sink's
    ``optimize`` events alone."""
    key = str(ev.get("mode", "?"))
    agg = optimize.setdefault(key, {
        "probes": 0, "improvements": 0, "proofs": 0, "misses": 0,
        "budget": 0, "improvement_total": 0, "probe_s": 0.0,
        "backend_wins": {},
    })
    agg["probes"] += 1
    try:
        agg["probe_s"] += float(ev.get("dur_s", 0.0) or 0.0)
    except (TypeError, ValueError):
        pass
    outcome = ev.get("outcome")
    if outcome == "improved":
        agg["improvements"] += 1
        try:
            agg["improvement_total"] += int(ev.get("improvement", 0) or 0)
        except (TypeError, ValueError):
            pass
        backend = str(ev.get("backend", "?"))
        agg["backend_wins"][backend] = \
            agg["backend_wins"].get(backend, 0) + 1
    elif outcome == "unsat":
        agg["proofs"] += 1
    elif outcome == "budget":
        agg["budget"] += 1
    else:
        agg["misses"] += 1


def _trip_regression(device: List[dict]) -> Optional[dict]:
    """solve_s ~ intercept + slope * trips over the sampled device
    dispatches.  None when the sink lacks two dispatches with distinct
    trip counts (a constant can't be regressed)."""
    import numpy as np

    pts = [(float(ev["trips"]), float(ev.get("solve_s", 0.0) or 0.0))
           for ev in device
           if ev.get("trips") is not None and ev.get("solve_s")]
    if len(pts) < 2:
        return None
    x = np.array([p[0] for p in pts])
    y = np.array([p[1] for p in pts])
    if float(x.max() - x.min()) <= 0:
        return None
    slope, intercept = np.polyfit(x, y, 1)
    pred = slope * x + intercept
    ss_res = float(((y - pred) ** 2).sum())
    ss_tot = float(((y - y.mean()) ** 2).sum())
    ratios = [float(ev.get("useful_work_ratio", 0.0) or 0.0)
              for ev in device]
    mean_useful = sum(ratios) / len(ratios) if ratios else 0.0
    return {
        "points": len(pts),
        "us_per_trip": round(float(slope) * 1e6, 3),
        "intercept_ms": round(float(intercept) * 1e3, 3),
        "r2": round(1.0 - ss_res / ss_tot, 4) if ss_tot > 0 else 1.0,
        "mean_useful_work_ratio": round(mean_useful, 4),
        "useful_us_per_trip": round(float(slope) * 1e6 * mean_useful, 3),
    }


def _size_classes(device: List[dict]) -> Dict[str, dict]:
    classes: Dict[str, dict] = {}
    for ev in device:
        # Ladder-class name when the event carries one (ISSUE 12);
        # older sinks fall back to the raw bucketed-cost key.
        key = str(ev.get("size_class_name")
                  or ev.get("size_class", "?"))
        agg = classes.setdefault(key, {
            "dispatches": 0, "lanes": 0, "live": 0, "trips": 0,
            "lane_steps": 0, "solve_s": 0.0,
            "_useful": 0.0, "_straggler": 0.0, "_pad": 0.0,
        })
        agg["dispatches"] += 1
        agg["lanes"] += int(ev.get("lanes", 0) or 0)
        agg["live"] += int(ev.get("live", 0) or 0)
        agg["trips"] += int(ev.get("trips", 0) or 0)
        agg["lane_steps"] += int(ev.get("lane_steps", 0) or 0)
        agg["solve_s"] += float(ev.get("solve_s", 0.0) or 0.0)
        agg["_useful"] += float(ev.get("useful_work_ratio", 0.0) or 0.0)
        agg["_straggler"] += float(
            ev.get("straggler_p99_ratio", 0.0) or 0.0)
        agg["_pad"] += float(ev.get("pad_waste_ratio", 0.0) or 0.0)
    for agg in classes.values():
        n = agg["dispatches"]
        agg["useful_work_ratio"] = round(agg.pop("_useful") / n, 4)
        agg["straggler_p99_ratio"] = round(agg.pop("_straggler") / n, 4)
        agg["pad_waste_ratio"] = round(agg.pop("_pad") / n, 4)
        agg["us_per_solve"] = (round(agg["solve_s"] * 1e6 / agg["live"], 2)
                               if agg["live"] else 0.0)
        agg["solve_s"] = round(agg["solve_s"], 6)
    return classes


def render_text(summary: dict, path: str) -> str:
    lines = [f"profile: {summary['profile_events']} profile events from "
             f"{path} ({summary['device_dispatches']} device dispatches)"]
    reg = summary.get("trip_overhead")
    if reg is not None:
        lines += [
            "trip overhead (solve wall ~ trips, least squares):",
            f"  {reg['us_per_trip']:.1f} us/trip  "
            f"(+{reg['intercept_ms']:.2f} ms fixed/dispatch, "
            f"r2={reg['r2']}, {reg['points']} dispatches)",
            f"  useful work: {reg['mean_useful_work_ratio']:.3f} of "
            f"trip-lane slots -> ~{reg['useful_us_per_trip']:.1f} "
            f"useful us/trip",
        ]
    else:
        lines.append(
            "trip overhead: not enough device dispatches with distinct "
            "trip counts (need >= 2; arm DEPPY_TPU_PROFILE=on and vary "
            "the workload)")
    classes = summary.get("size_classes") or {}
    if classes:
        lines.append("size classes:")
        lines.append(f"  {'class':>10}  {'disp':>5}  {'live':>6}  "
                     f"{'trips':>8}  {'useful':>7}  {'p99/trip':>8}  "
                     f"{'padwaste':>8}  {'us/solve':>9}")
        for key in sorted(classes, key=lambda k: (len(k), k)):
            a = classes[key]
            lines.append(
                f"  {key:>10}  {a['dispatches']:>5}  {a['live']:>6}  "
                f"{a['trips']:>8}  {a['useful_work_ratio']:>7.3f}  "
                f"{a['straggler_p99_ratio']:>8.3f}  "
                f"{a['pad_waste_ratio']:>8.3f}  {a['us_per_solve']:>9.1f}")
    backends = summary.get("backends") or {}
    if backends:
        lines.append("backends:")
        lines.append(f"  {'backend':>10}  {'events':>6}  {'lanes':>7}  "
                     f"{'solve_s':>9}  {'us/solve':>9}")
        for name in sorted(backends):
            a = backends[name]
            lines.append(f"  {name:>10}  {a['events']:>6}  "
                         f"{a['lanes']:>7}  {a['solve_s']:>9.3f}  "
                         f"{a['us_per_solve']:>9.1f}")
    races = summary.get("races") or {}
    if races:
        lines.append("portfolio races (per size class):")
        lines.append(f"  {'class':>10}  {'races':>5}  "
                     f"{'wins':<28}  {'cancels':<24}  {'margin':>8}  "
                     f"{'resub':>5}")
        for key in sorted(races, key=lambda k: (len(k), k)):
            a = races[key]
            wins = " ".join(f"{n}={c}" for n, c in
                            sorted(a["wins"].items())) or "-"
            cancels = " ".join(f"{n}={c}" for n, c in
                               sorted(a["cancels"].items())) or "-"
            margin = (f"{a['win_margin_s_mean'] * 1e3:.1f}ms"
                      if a.get("win_margin_s_mean") is not None else "-")
            lines.append(
                f"  {key:>10}  {a['races']:>5}  {wins:<28}  "
                f"{cancels:<24}  {margin:>8}  {a['resubmitted']:>5}")
            # Censored-aware backend speed (ISSUE 19): µs/lane from
            # FINISHED entrants only, with the censored (cancelled)
            # observation count alongside so a backend that always
            # loses by cancellation reads "unmeasured", not "fast".
            speed = a.get("backend_us_per_lane") or {}
            if speed:
                cells = []
                for b in sorted(set(speed) | set(a.get("censored") or {})):
                    row = speed.get(b)
                    cen = (a.get("censored") or {}).get(b, 0)
                    cell = (f"{b}={row['us_per_lane']:.0f}us/{row['samples']}"
                            if row else f"{b}=?")
                    if cen:
                        cell += f" (cens {cen})"
                    cells.append(cell)
                lines.append(f"  {'':>10}  speed: " + "  ".join(cells))
            if a.get("check_mismatches"):
                lines.append(
                    f"  {'':>10}  !! {a['check_mismatches']} sampled "
                    f"cross-check mismatch(es) — served canonical")
    optimize = summary.get("optimize") or {}
    if optimize:
        lines.append("optimization probes (per mode):")
        lines.append(f"  {'mode':>10}  {'probes':>6}  {'improved':>8}  "
                     f"{'proofs':>6}  {'miss':>5}  {'budget':>6}  "
                     f"{'delta/imp':>9}  {'ms/probe':>8}  "
                     f"{'backend wins':<24}")
        for key in sorted(optimize):
            a = optimize[key]
            wins = " ".join(f"{n}={c}" for n, c in
                            sorted(a["backend_wins"].items())) or "-"
            mean = a.get("improvement_mean")
            per = (a["probe_s"] * 1e3 / a["probes"]
                   if a["probes"] else 0.0)
            lines.append(
                f"  {key:>10}  {a['probes']:>6}  {a['improvements']:>8}  "
                f"{a['proofs']:>6}  {a['misses']:>5}  {a['budget']:>6}  "
                f"{mean if mean is not None else '-':>9}  {per:>8.2f}  "
                f"{wins:<24}")
    return "\n".join(lines)
