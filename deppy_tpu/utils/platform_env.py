"""Forced-platform environment provisioning for driver entry points.

One strip-and-replace recipe shared by ``bench.py`` and
``__graft_entry__.dryrun_multichip`` (and usable by tests): on this
machine a sitecustomize hook registers a TPU PJRT plugin whose init can
hang, and ``JAX_PLATFORMS=cpu`` in the environment alone is not honored
by it — subprocesses must BOTH carry this env and call
``jax.config.update("jax_platforms", "cpu")`` before the first backend
query (the ``tests/conftest.py`` recipe).
"""

from __future__ import annotations

import os
from typing import Mapping


def assert_env_platform() -> None:
    """Make ``JAX_PLATFORMS`` from the environment actually stick.

    The env var alone only steers backend *selection*; jax still
    *initializes* every registered PJRT plugin during discovery — and on
    this machine the sitecustomize-registered axon TPU plugin's init
    hangs whenever the tunneled worker is down (observed 2026-07-31: a
    ``JAX_PLATFORMS=cpu`` process hung in ``jax.default_backend()``
    while the worker was wedged).  Setting ``jax.config`` limits
    discovery itself to the named platforms, so a forced-CPU process
    never touches the plugin.  Must run before the first backend query;
    harmlessly idempotent with tests/conftest.py's identical update."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)


def apply_platform_env() -> None:
    """Process-entry-point provisioning: :func:`assert_env_platform` plus
    the persistent compilation cache (see :func:`enable_compile_cache`).
    Called by every process entry point (CLI, service, benchmarks) so
    ``JAX_PLATFORMS=cpu python -m deppy_tpu ...`` behaves as documented —
    in particular it cannot hang on a crashed/restarting TPU worker."""
    assert_env_platform()
    enable_compile_cache()


def run_captured(cmd, timeout_s, env=None, cwd=None):
    """``subprocess.run(capture_output=True, timeout=...)`` that cannot
    re-hang after the timeout.

    Plain ``subprocess.run`` with captured pipes handles TimeoutExpired by
    killing only the direct child and then blocking until pipe EOF — a
    wedged runtime helper process (e.g. a libtpu child stuck on a crashed
    worker) that inherited the pipes keeps them open and re-hangs the
    parent indefinitely.  This variant starts the child in its own
    session and kills the whole process group on timeout, so EOF is
    guaranteed.  Returns ``(returncode, stdout, stderr)`` or raises
    ``subprocess.TimeoutExpired``."""
    import signal
    import subprocess

    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        cwd=cwd,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()  # at least the direct child dies
        try:
            # Group normally dead -> EOF immediate; the bound covers an
            # unsignalable group member still holding the pipes.
            out, err = proc.communicate(timeout=5)
        except subprocess.TimeoutExpired:
            out, err = "", ""
        # Mirror subprocess.run: the partial output rides the exception
        # so callers can log what the child was doing when it hung.
        raise subprocess.TimeoutExpired(
            cmd, timeout_s, output=out, stderr=err
        ) from None
    return proc.returncode, out, err


# One probe source for every backend-health check in the tree
# (tpu_doctor, bench.py, sat/solver.py's auto-routing): PJRT init and a
# tiny compile+execute+readback, each stage marked on stdout.  Init
# alone is NOT health — a wedged worker can answer ``jax.devices()`` and
# then hang the first compile for 20+ minutes (observed 2026-07-31).
# JAX_PLATFORMS is re-asserted because this machine's sitecustomize
# imports jax at interpreter startup and pins the plugin otherwise.
_PROBE_SRC_TEMPLATE = (
    "import signal; signal.alarm({alarm}); "
    "import os, time, jax; "
    "p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); "
    "t0 = time.time(); d = jax.devices(); "
    "print('INIT', jax.default_backend(), len(d), round(time.time()-t0, 1),"
    " flush=True); "
    "import jax.numpy as jnp; "
    "t1 = time.time(); x = jnp.ones((8, 8), jnp.float32); "
    "v = float((x @ x).sum()); "
    "print('COMPUTE', v, round(time.time()-t1, 1), flush=True)"
    "{epilogue}; os._exit(0)"
)


def probe_src(alarm_s: int, epilogue: str = "") -> str:
    """Source for a disposable backend-health probe subprocess.

    ``alarm_s`` arms a SIGALRM self-destruct (default disposition kills
    the process even while blocked inside PJRT C code) so an ORPHANED
    probe — its caller killed mid-probe; probes run in their own session
    — cannot hang in init for hours holding the worker connection (an
    orphan exactly like that was found alive after a timed-out bench run
    on 2026-07-31).  ``epilogue`` is inserted verbatim after the COMPUTE
    stage (e.g. ``"; import deppy_tpu.engine.driver"``); the probe then
    always ``os._exit(0)``s so PJRT teardown — which can itself hang on
    a sick worker — never runs inside the caller's timed window and a
    healthy backend cannot be misread as a compute hang.

    Stdout carries one line per completed stage (``INIT <backend>
    <n_devices> <s>``, then ``COMPUTE <checksum> <s>``), so a caller
    catching a timeout can tell which stage hung from the partial output
    that rides :func:`run_captured`'s ``TimeoutExpired``.  Parse with
    :func:`parse_probe_stages`."""
    return _PROBE_SRC_TEMPLATE.format(alarm=alarm_s, epilogue=epilogue)


def parse_probe_stages(stdout: str) -> dict:
    """Parse :func:`probe_src` stage lines (full or partial output).

    Returns a dict with any of ``backend``/``n_devices``/``init_s``
    (from the INIT line) and ``compute_s`` (from the COMPUTE line) that
    were present — the single parser for the single format, shared by
    tpu_doctor and bench.py so the two cannot drift."""
    out: dict = {}
    for line in (stdout or "").splitlines():
        parts = line.split()
        if parts[:1] == ["INIT"] and len(parts) >= 4:
            out["backend"] = parts[1]
            try:
                out["n_devices"] = int(parts[2])
                out["init_s"] = float(parts[3])
            except ValueError:
                pass
        elif parts[:1] == ["COMPUTE"] and len(parts) >= 3:
            try:
                out["compute_s"] = float(parts[2])
            except ValueError:
                pass
    return out


def default_cache_dir() -> str:
    """The persistent compilation cache's default location — single
    source for :func:`enable_compile_cache` and opt-in callers (e.g.
    ``bench.py``'s accelerator subprocess)."""
    return os.path.join(os.path.expanduser("~"), ".cache", "deppy_tpu",
                        "xla")


def enable_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a stable directory.

    The engine compiles one executable per padded shape bucket; a cold
    process pays 10-40s of warm-up for the first solve of each shape.
    With the persistent cache, any shape ever compiled on this machine
    (per backend) loads from disk in milliseconds — cutting service
    cold-start and benchmark warm-up after the first run.

    ``DEPPY_TPU_COMPILE_CACHE`` overrides the directory; ``off`` (or
    ``0``, any case) disables.  Never fails: a read-only home or an old
    JAX just leaves caching off.

    Default-on only when ``JAX_PLATFORMS`` names a non-CPU platform:
    XLA:CPU's AOT cache loader warns about compile-vs-host
    machine-feature mismatches ("could lead to SIGILL"), so CPU-backed
    processes — forced-CPU tests/bench fallback AND machines where the
    platform is simply unset and resolves to CPU — skip it unless the
    env var explicitly opts in.  ``bench.py`` opts its accelerator
    subprocess in explicitly (the platform env is unset there so the
    PJRT plugin resolves)."""
    from .. import config

    path = config.env_raw("DEPPY_TPU_COMPILE_CACHE")
    if path is not None:
        token = path.strip().lower()
        if token in ("off", "0", ""):
            return
        if token in ("on", "1", "true"):
            path = default_cache_dir()
    if path is None:
        platforms = (os.environ.get("JAX_PLATFORMS") or "").strip()
        if not platforms or platforms == "cpu":
            return
        path = default_cache_dir()
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Default thresholds skip small/fast programs; the engine's many
        # per-shape executables are exactly what we want cached.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # deppy: lint-ok[exception-hygiene] cache is an optimization: read-only home / old jax leaves it off
    except Exception:
        pass


def force_cpu_env(environ: Mapping[str, str], n_devices: int = 1) -> dict:
    """Copy ``environ`` with the virtual-CPU platform forced: sets
    ``JAX_PLATFORMS=cpu`` and replaces (never merely keeps) any existing
    ``--xla_force_host_platform_device_count`` flag with ``n_devices``."""
    if n_devices <= 0:
        raise ValueError("n_devices must be positive")
    env = dict(environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env
