"""Forced-platform environment provisioning for driver entry points.

One strip-and-replace recipe shared by ``bench.py`` and
``__graft_entry__.dryrun_multichip`` (and usable by tests): on this
machine a sitecustomize hook registers a TPU PJRT plugin whose init can
hang, and ``JAX_PLATFORMS=cpu`` in the environment alone is not honored
by it — subprocesses must BOTH carry this env and call
``jax.config.update("jax_platforms", "cpu")`` before the first backend
query (the ``tests/conftest.py`` recipe).
"""

from __future__ import annotations

import os
from typing import Mapping


def apply_platform_env() -> None:
    """Make ``JAX_PLATFORMS`` from the environment actually stick.

    The baked sitecustomize registers the axon TPU plugin at interpreter
    start and pins the platform selection, so the env var alone is ignored
    by the time user code runs; re-asserting it through ``jax.config``
    before the first backend query restores the standard semantics.  Called
    by every process entry point (CLI, service, benchmarks) so
    ``JAX_PLATFORMS=cpu python -m deppy_tpu ...`` behaves as documented —
    in particular it cannot hang on a crashed/restarting TPU worker."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)


def force_cpu_env(environ: Mapping[str, str], n_devices: int = 1) -> dict:
    """Copy ``environ`` with the virtual-CPU platform forced: sets
    ``JAX_PLATFORMS=cpu`` and replaces (never merely keeps) any existing
    ``--xla_force_host_platform_device_count`` flag with ``n_devices``."""
    if n_devices <= 0:
        raise ValueError("n_devices must be positive")
    env = dict(environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env
