"""Forced-platform environment provisioning for driver entry points.

One strip-and-replace recipe shared by ``bench.py`` and
``__graft_entry__.dryrun_multichip`` (and usable by tests): on this
machine a sitecustomize hook registers a TPU PJRT plugin whose init can
hang, and ``JAX_PLATFORMS=cpu`` in the environment alone is not honored
by it — subprocesses must BOTH carry this env and call
``jax.config.update("jax_platforms", "cpu")`` before the first backend
query (the ``tests/conftest.py`` recipe).
"""

from __future__ import annotations

import os
from typing import Mapping


def apply_platform_env() -> None:
    """Make ``JAX_PLATFORMS`` from the environment actually stick.

    The baked sitecustomize registers the axon TPU plugin at interpreter
    start and pins the platform selection, so the env var alone is ignored
    by the time user code runs; re-asserting it through ``jax.config``
    before the first backend query restores the standard semantics.  Called
    by every process entry point (CLI, service, benchmarks) so
    ``JAX_PLATFORMS=cpu python -m deppy_tpu ...`` behaves as documented —
    in particular it cannot hang on a crashed/restarting TPU worker.

    Also enables the persistent compilation cache (see
    :func:`enable_compile_cache`)."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
    enable_compile_cache()


def run_captured(cmd, timeout_s, env=None, cwd=None):
    """``subprocess.run(capture_output=True, timeout=...)`` that cannot
    re-hang after the timeout.

    Plain ``subprocess.run`` with captured pipes handles TimeoutExpired by
    killing only the direct child and then blocking until pipe EOF — a
    wedged runtime helper process (e.g. a libtpu child stuck on a crashed
    worker) that inherited the pipes keeps them open and re-hangs the
    parent indefinitely.  This variant starts the child in its own
    session and kills the whole process group on timeout, so EOF is
    guaranteed.  Returns ``(returncode, stdout, stderr)`` or raises
    ``subprocess.TimeoutExpired``."""
    import signal
    import subprocess

    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        cwd=cwd,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()  # at least the direct child dies
        try:
            # Group normally dead -> EOF immediate; the bound covers an
            # unsignalable group member still holding the pipes.
            out, err = proc.communicate(timeout=5)
        except subprocess.TimeoutExpired:
            out, err = "", ""
        # Mirror subprocess.run: the partial output rides the exception
        # so callers can log what the child was doing when it hung.
        raise subprocess.TimeoutExpired(
            cmd, timeout_s, output=out, stderr=err
        ) from None
    return proc.returncode, out, err


def default_cache_dir() -> str:
    """The persistent compilation cache's default location — single
    source for :func:`enable_compile_cache` and opt-in callers (e.g.
    ``bench.py``'s accelerator subprocess)."""
    return os.path.join(os.path.expanduser("~"), ".cache", "deppy_tpu",
                        "xla")


def enable_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a stable directory.

    The engine compiles one executable per padded shape bucket; a cold
    process pays 10-40s of warm-up for the first solve of each shape.
    With the persistent cache, any shape ever compiled on this machine
    (per backend) loads from disk in milliseconds — cutting service
    cold-start and benchmark warm-up after the first run.

    ``DEPPY_TPU_COMPILE_CACHE`` overrides the directory; ``off`` (or
    ``0``, any case) disables.  Never fails: a read-only home or an old
    JAX just leaves caching off.

    Default-on only when ``JAX_PLATFORMS`` names a non-CPU platform:
    XLA:CPU's AOT cache loader warns about compile-vs-host
    machine-feature mismatches ("could lead to SIGILL"), so CPU-backed
    processes — forced-CPU tests/bench fallback AND machines where the
    platform is simply unset and resolves to CPU — skip it unless the
    env var explicitly opts in.  ``bench.py`` opts its accelerator
    subprocess in explicitly (the platform env is unset there so the
    PJRT plugin resolves)."""
    path = os.environ.get("DEPPY_TPU_COMPILE_CACHE")
    if path is not None:
        token = path.strip().lower()
        if token in ("off", "0", ""):
            return
        if token in ("on", "1", "true"):
            path = default_cache_dir()
    if path is None:
        platforms = (os.environ.get("JAX_PLATFORMS") or "").strip()
        if not platforms or platforms == "cpu":
            return
        path = default_cache_dir()
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Default thresholds skip small/fast programs; the engine's many
        # per-shape executables are exactly what we want cached.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def force_cpu_env(environ: Mapping[str, str], n_devices: int = 1) -> dict:
    """Copy ``environ`` with the virtual-CPU platform forced: sets
    ``JAX_PLATFORMS=cpu`` and replaces (never merely keeps) any existing
    ``--xla_force_host_platform_device_count`` flag with ``n_devices``."""
    if n_devices <= 0:
        raise ValueError("n_devices must be positive")
    env = dict(environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env
