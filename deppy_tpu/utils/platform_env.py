"""Forced-platform environment provisioning for driver entry points.

One strip-and-replace recipe shared by ``bench.py`` and
``__graft_entry__.dryrun_multichip`` (and usable by tests): on this
machine a sitecustomize hook registers a TPU PJRT plugin whose init can
hang, and ``JAX_PLATFORMS=cpu`` in the environment alone is not honored
by it — subprocesses must BOTH carry this env and call
``jax.config.update("jax_platforms", "cpu")`` before the first backend
query (the ``tests/conftest.py`` recipe).
"""

from __future__ import annotations

from typing import Mapping


def force_cpu_env(environ: Mapping[str, str], n_devices: int = 1) -> dict:
    """Copy ``environ`` with the virtual-CPU platform forced: sets
    ``JAX_PLATFORMS=cpu`` and replaces (never merely keeps) any existing
    ``--xla_force_host_platform_device_count`` flag with ``n_devices``."""
    if n_devices <= 0:
        raise ValueError("n_devices must be positive")
    env = dict(environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env
