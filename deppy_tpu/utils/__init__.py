"""Shared utilities: solution verification, timing helpers."""

from .verify import check_solution

__all__ = ["check_solution"]
