"""Shared utilities: solution verification, platform provisioning."""

from .platform_env import force_cpu_env
from .verify import check_solution

__all__ = ["check_solution", "force_cpu_env"]
