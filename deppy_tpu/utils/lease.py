"""Lease-based leader election over the Kubernetes coordination API.

Analog of the reference manager's controller-runtime leader election
(/root/reference/main.go:51,62-69: ``LeaderElection: enableLeaderElection``
with ``LeaderElectionID``).  The reference needs election because its
manager hosts reconcile loops that must run exactly once per cluster.
This rebuild's service is a stateless resolve API — the default HA
topology is active-active replicas behind a Service, no election
required — but operators running an accelerator-budgeted **hot-standby
pair** (one pod holding the TPU, one warm spare) want exactly one pod
serving at a time.  That is what this module provides: only the lease
holder reports ready on ``/readyz``, so the Service's endpoints carry
exactly one pod and failover is a lease takeover away.

Implementation notes:

* Talks to ``coordination.k8s.io/v1`` Lease objects directly with the
  stdlib (``urllib`` + ``ssl``) — the image ships no kubernetes client,
  and the election protocol is three verbs (GET/POST/PUT) plus
  optimistic concurrency via ``metadata.resourceVersion``.  The RBAC
  verbs required are exactly what ``config/rbac/leader_election_role.yaml``
  grants.
* The algorithm mirrors client-go's leaderelection: create the lease if
  absent; renew it while held; take it over when the holder's
  ``renewTime`` is more than ``leaseDurationSeconds`` stale.  Every
  write carries the read's ``resourceVersion``, so a lost race is a 409,
  never a split brain.
* Failure posture is **fail-closed**: a tick that cannot read or write
  the API drops leadership immediately (flipping ``/readyz`` to 503)
  rather than coasting on the last known state.  For a readiness gate
  the cost of a false negative is a moment of unavailability; the cost
  of a false positive is two pods serving — so negatives win.
* ``stop(release=True)`` clears ``holderIdentity`` so the standby takes
  over on its next tick instead of waiting out the lease duration —
  the same graceful-handoff client-go performs on shutdown.
"""

from __future__ import annotations

import json
import os
import random
import socket
import ssl
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from datetime import datetime, timedelta, timezone
from typing import Callable, Optional

_RFC3339_MICRO = "%Y-%m-%dT%H:%M:%S.%fZ"

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def _now() -> datetime:
    return datetime.now(timezone.utc)


def _fmt_time(t: datetime) -> str:
    return t.astimezone(timezone.utc).strftime(_RFC3339_MICRO)


def _parse_time(s: str) -> Optional[datetime]:
    # The API server emits RFC3339 with or without fractional seconds.
    for fmt in (_RFC3339_MICRO, "%Y-%m-%dT%H:%M:%SZ"):
        try:
            return datetime.strptime(s, fmt).replace(tzinfo=timezone.utc)
        except ValueError:
            continue
    return None


@dataclass
class LeaseConfig:
    """Where the lease lives and who we claim to be."""

    name: str
    namespace: str = "deppy-tpu-system"
    identity: str = field(default_factory=socket.gethostname)
    api_base: str = ""          # e.g. https://10.0.0.1:443 (in-cluster)
    token: Optional[str] = None
    ca_path: Optional[str] = None
    lease_seconds: int = 15
    renew_seconds: float = 0.0  # 0 → lease_seconds / 3
    # Random fraction of the renew interval added to each tick's wait: a
    # hot-standby pair whose pods started together would otherwise renew
    # in lockstep and hammer the API server at the same instants forever.
    renew_jitter: float = 0.2

    def __post_init__(self) -> None:
        if self.renew_seconds <= 0:
            self.renew_seconds = max(self.lease_seconds / 3.0, 0.2)
        self.renew_jitter = min(max(self.renew_jitter, 0.0), 1.0)

    @property
    def url(self) -> str:
        return (f"{self.api_base}/apis/coordination.k8s.io/v1/namespaces/"
                f"{self.namespace}/leases/{self.name}")

    @property
    def create_url(self) -> str:
        return (f"{self.api_base}/apis/coordination.k8s.io/v1/namespaces/"
                f"{self.namespace}/leases")


def in_cluster_config(name: str, lease_seconds: int = 15) -> LeaseConfig:
    """Build a :class:`LeaseConfig` from the pod's mounted service account
    (the standard in-cluster discovery: env for the API address, files for
    token/CA/namespace)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        raise RuntimeError(
            "KUBERNETES_SERVICE_HOST not set: not running in a cluster "
            "(set DEPPY_HA_API to point at an API server explicitly)")
    token = None
    namespace = "deppy-tpu-system"
    try:
        with open(os.path.join(_SA_DIR, "token")) as f:
            token = f.read().strip()
        with open(os.path.join(_SA_DIR, "namespace")) as f:
            namespace = f.read().strip()
    except OSError:
        pass
    ca = os.path.join(_SA_DIR, "ca.crt")
    return LeaseConfig(
        name=name, namespace=namespace,
        api_base=f"https://{host}:{port}", token=token,
        ca_path=ca if os.path.exists(ca) else None,
        lease_seconds=lease_seconds,
    )


class LeaseElector:
    """Acquire/renew a Lease on a background thread; expose ``is_leader``.

    ``on_change(bool)`` fires on every leadership transition (under no
    locks — keep it cheap; the service uses it to log and bump a gauge).
    """

    def __init__(self, config: LeaseConfig,
                 on_change: Optional[Callable[[bool], None]] = None):
        self.config = config
        self.on_change = on_change
        self._leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ctx: Optional[ssl.SSLContext] = None
        if config.api_base.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=config.ca_path)

    # -- HTTP plumbing ----------------------------------------------------

    def _request(self, method: str, url: str,
                 body: Optional[dict] = None) -> tuple:
        """Returns (status, parsed-json-or-None); network errors raise."""
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.config.token:
            req.add_header("Authorization", f"Bearer {self.config.token}")
        try:
            with urllib.request.urlopen(req, timeout=5,
                                        context=self._ctx) as resp:
                payload = resp.read()
                return resp.status, (json.loads(payload) if payload else None)
        except urllib.error.HTTPError as e:
            # 404 (absent) and 409 (lost race) are protocol states, not
            # failures; read the body so the connection is reusable.
            e.read()
            return e.code, None

    # -- election protocol -------------------------------------------------

    def _lease_body(self, acquire: bool, transitions: int,
                    prev_acquire: Optional[str]) -> dict:
        now = _fmt_time(_now())
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.config.name,
                         "namespace": self.config.namespace},
            "spec": {
                "holderIdentity": self.config.identity,
                "leaseDurationSeconds": self.config.lease_seconds,
                "acquireTime": now if acquire else (prev_acquire or now),
                "renewTime": now,
                "leaseTransitions": transitions,
            },
        }

    def tick(self) -> bool:
        """One election step; returns the resulting leadership verdict.
        Exposed for tests — the background loop just calls this on the
        renew interval."""
        try:
            verdict = self._tick_inner()
        # deppy: lint-ok[exception-hygiene] fail-closed by design: verdict=False flips /readyz
        except Exception:
            # Fail closed (see module docstring): unreachable OR
            # misbehaving API ⇒ not leader, so /readyz flips rather than
            # risking two actives.  Deliberately broad — a truncated
            # response raises http.client.HTTPException (not OSError),
            # and ANY escape would kill the election thread, freezing
            # leadership at its last value: the one unrecoverable state.
            verdict = False
        self._set_leader(verdict)
        return verdict

    def _tick_inner(self) -> bool:
        status, doc = self._request("GET", self.config.url)
        if status == 404:
            body = self._lease_body(acquire=True, transitions=0,
                                    prev_acquire=None)
            status, _ = self._request("POST", self.config.create_url, body)
            return 200 <= status < 300  # 409 ⇒ another replica created it
        if not (200 <= status < 300) or doc is None:
            return False

        spec = doc.get("spec") or {}
        holder = spec.get("holderIdentity") or ""
        transitions = int(spec.get("leaseTransitions") or 0)
        duration = int(spec.get("leaseDurationSeconds")
                       or self.config.lease_seconds)
        renew = _parse_time(spec.get("renewTime") or "")
        expired = (holder == "" or renew is None
                   or _now() > renew + timedelta(seconds=duration))

        if holder != self.config.identity and not expired:
            return False  # healthy foreign holder

        # Renew (ours) or take over (vacant/expired) — same guarded PUT.
        taking_over = holder != self.config.identity
        body = self._lease_body(
            acquire=taking_over,
            transitions=transitions + (1 if taking_over else 0),
            prev_acquire=spec.get("acquireTime"),
        )
        # The read's resourceVersion is the optimistic-concurrency guard:
        # if anyone wrote between our GET and PUT, the PUT 409s and we
        # re-evaluate next tick.
        rv = (doc.get("metadata") or {}).get("resourceVersion")
        if rv is not None:
            body["metadata"]["resourceVersion"] = rv
        status, _ = self._request("PUT", self.config.url, body)
        return 200 <= status < 300

    def release(self) -> None:
        """Graceful handoff: blank the holder so the standby's next tick
        takes over immediately instead of waiting out the duration."""
        try:
            status, doc = self._request("GET", self.config.url)
            if not (200 <= status < 300) or doc is None:
                return
            spec = doc.get("spec") or {}
            if (spec.get("holderIdentity") or "") != self.config.identity:
                return
            spec["holderIdentity"] = ""
            self._request("PUT", self.config.url, doc)
        # deppy: lint-ok[exception-hygiene] best-effort release; lease expiry bounds the outage
        except Exception:
            pass  # best effort; expiry still bounds the outage

    # -- lifecycle ---------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self._leader

    def _set_leader(self, value: bool) -> None:
        if value != self._leader:
            self._leader = value
            if self.on_change is not None:
                try:
                    self.on_change(value)
                # deppy: lint-ok[exception-hygiene] observer errors must not break election
                except Exception:
                    pass  # observer errors must not break election

    def _renew_wait(self, elapsed: float,
                    rng=random.random) -> float:
        """Sleep before the next tick: the renew interval plus up to
        ``renew_jitter`` of it at random (desynchronizing hot-standby
        pairs), minus the time the tick itself took.  Clamped to a small
        floor so a tick that overruns its interval (slow/flapping API
        server) degrades to closely spaced renews instead of a
        negative-wait hot loop — and the schedule doesn't drift by the
        tick's own latency."""
        base = self.config.renew_seconds
        jitter = base * self.config.renew_jitter * rng()
        return max(base + jitter - max(elapsed, 0.0), base * 0.05)

    def start(self) -> None:
        def _loop():
            while not self._stop.is_set():
                t0 = time.monotonic()
                self.tick()
                self._stop.wait(self._renew_wait(time.monotonic() - t0))

        self._thread = threading.Thread(target=_loop, daemon=True)
        self._thread.start()

    def stop(self, release: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if release:
            # Unconditional, NOT gated on self._leader: a transient API
            # error on the final tick clears the local flag while the
            # server-side lease still names this pod with a fresh
            # renewTime — skipping the handoff there would make the
            # drain wait out full lease expiry.  release() verifies the
            # holder server-side, so calling it as a non-holder is a
            # cheap no-op.
            self.release()
        self._set_leader(False)


def elector_from_env() -> Optional[LeaseElector]:
    """Build the service's elector from the environment, or None when HA
    election is off (the default — stateless active-active needs none).

    ``DEPPY_HA_LEASE``           lease name; empty/unset disables.
    ``DEPPY_HA_API``             API base URL override (tests / kubeconfig
                                 proxies); default in-cluster discovery.
    ``DEPPY_HA_NAMESPACE``       lease namespace override.
    ``DEPPY_HA_LEASE_SECONDS``   lease duration (default 15).
    """
    name = os.environ.get("DEPPY_HA_LEASE", "").strip()
    if not name:
        return None
    try:
        seconds = int(os.environ.get("DEPPY_HA_LEASE_SECONDS", "15"))
    except ValueError:
        seconds = 15
    if seconds < 1:
        seconds = 15
    api = os.environ.get("DEPPY_HA_API", "").strip()
    if api:
        cfg = LeaseConfig(name=name, api_base=api, lease_seconds=seconds)
        ns = os.environ.get("DEPPY_HA_NAMESPACE", "").strip()
        if ns:
            cfg.namespace = ns
    else:
        cfg = in_cluster_config(name, lease_seconds=seconds)
        ns = os.environ.get("DEPPY_HA_NAMESPACE", "").strip()
        if ns:
            cfg.namespace = ns
    return LeaseElector(cfg)
