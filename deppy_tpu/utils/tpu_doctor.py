"""TPU backend diagnostics: root-cause a hanging/failing accelerator init.

Rounds 1-2 of this build lost every TPU measurement to an "init hang" no
one could explain.  Round 3 root-caused it (see BASELINE.md TPU notes):

  * programs with too many vmap lanes reproducibly crash the tunneled
    worker (the engine now chunks dispatches, driver.MAX_LANES), and so
    do minutes-long single program executions (the engine now host-routes
    giant-problem core extraction, driver.HOST_CORE_NCONS);
  * a crashed worker then makes PJRT init HANG for minutes while it
    restarts — so "init hangs" is usually "worker is restarting", and the
    right response is a bounded wait + retry, not a fast fallback;
  * killing a probe mid-init can wedge the client side too, so probes must
    run in disposable subprocesses.

This module packages those findings as a tool: ``python -m
deppy_tpu.utils.tpu_doctor`` probes the backend in a subprocess with a
timeout, classifies the outcome (healthy / worker-restarting / plugin
failure / no accelerator), reports suspicious sibling processes that may
be holding the chip, and exits 0 only on a healthy accelerator.  bench.py
embeds the same retry logic; this is the standalone "why is my TPU not
answering" entry point.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

# The probe source lives in platform_env.probe_src (shared with bench.py
# and sat/solver.py's auto-routing): SIGALRM self-destruct, PJRT init,
# then a tiny compile+execute — init alone is NOT health, a wedged
# worker can answer ``jax.devices()`` and then hang the first compile
# for 20+ minutes (observed 2026-07-31; that probe-then-hang gap cost a
# full benchmark timeout).  Stage markers on stdout (INIT / COMPUTE)
# ride the TimeoutExpired so _probe can tell WHICH stage hung.


def _probe(timeout_s: int) -> dict:
    """One subprocess probe.  Returns {status, backend?, init_s?, detail}.
    status: ok / cpu-only / error / hang (PJRT init never answered) /
    compute-hang (init answered, first compile+execute wedged — a sicker
    worker than a restarting one: init hangs clear in minutes, observed
    compute wedges have lasted hours).

    Uses :func:`platform_env.run_captured` so a wedged runtime helper
    holding the pipes cannot re-hang the doctor past its own timeout."""
    from .platform_env import parse_probe_stages, probe_src, run_captured

    try:
        rc, stdout, stderr = run_captured(
            [sys.executable, "-c", probe_src(timeout_s + 10)],
            timeout_s=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        partial = (e.output or "").strip()
        if "INIT" in partial:
            return {
                "status": "compute-hang",
                "detail": (
                    f"init ok ({partial.splitlines()[0]}) but a tiny "
                    f"compile+execute exceeded {timeout_s}s"
                ),
            }
        return {"status": "hang", "detail": f"init exceeded {timeout_s}s"}
    if rc != 0:
        tail = (stderr or "").strip().splitlines()[-3:]
        return {"status": "error", "detail": " | ".join(tail)}
    stages = parse_probe_stages(stdout)
    backend = stages.get("backend", "?")
    if backend == "?":
        # rc==0 but no parseable INIT line: the probe ran but its output
        # is garbage — that's a harness bug or output loss, not evidence
        # of a CPU-only host.  Classifying it "cpu-only" once made
        # diagnose() report "no accelerator" for a probe that succeeded.
        return {
            "status": "error",
            "detail": ("probe exited 0 with unparseable output: "
                       + repr((stdout or "").strip()[-200:])),
        }
    return {
        "status": "ok" if backend != "cpu" else "cpu-only",
        "backend": backend,
        # True per-stage timings from the probe's own clock (wall time
        # here would also count interpreter start + jax import).
        "init_s": stages.get("init_s"),
        "compute_s": stages.get("compute_s"),
        "detail": "; ".join((stdout or "").strip().splitlines()),
    }


def _chip_holders() -> list:
    """Best-effort list of other python processes that might hold the chip
    (a held chip makes init fail or hang until they exit)."""
    me = os.getpid()
    holders = []
    try:
        out = subprocess.run(
            ["pgrep", "-af", "python"], capture_output=True, text=True,
            timeout=10,
        )
        for line in (out.stdout or "").splitlines():
            pid_s, _, cmd = line.partition(" ")
            if "tpu_doctor" in cmd:  # ourselves / our parent shell
                continue
            if pid_s.isdigit() and int(pid_s) != me and (
                "jax" in cmd or "deppy" in cmd or "bench" in cmd
            ):
                # Truncate: agent/driver wrappers can carry multi-KB
                # command lines, and the report only needs the gist.
                cmd = cmd.strip()
                if len(cmd) > 160:
                    cmd = cmd[:160] + " ...[truncated]"
                holders.append(f"{pid_s} {cmd}")
    except (OSError, subprocess.TimeoutExpired):
        pass
    return holders


def diagnose(probe_timeout: int = 120, retries: int = 3,
             retry_delay: int = 90) -> int:
    """Run the diagnosis; prints a human report to stderr, returns an exit
    code: 0 healthy accelerator, 1 worker-restart suspected (retry in
    minutes), 2 plugin/config failure, 3 no accelerator configured,
    4 worker compute-wedged (init answers, compute hangs — observed
    recoveries take hours; no point retrying on a minutes scale, so this
    verdict short-circuits the retry loop)."""
    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    plat = os.environ.get("JAX_PLATFORMS", "(unset)")
    log(f"JAX_PLATFORMS={plat}")
    hangs = 0
    for attempt in range(1, retries + 1):
        log(f"probe {attempt}/{retries} (timeout {probe_timeout}s)...")
        r = _probe(probe_timeout)
        if r["status"] == "ok":
            log(f"HEALTHY: backend={r['backend']} init={r['init_s']}s "
                f"compute={r.get('compute_s')}s ({r['detail']})")
            return 0
        if r["status"] == "cpu-only":
            log("NO ACCELERATOR: jax resolved to the CPU backend — either "
                "JAX_PLATFORMS pins cpu or no TPU plugin is registered.")
            return 3
        if r["status"] == "error":
            log(f"PLUGIN FAILURE: probe crashed: {r['detail']}")
            log("Likely a config/env problem, not a busy worker; fix the "
                "plugin before retrying.")
            return 2
        hangs += 1
        if r["status"] == "compute-hang":
            log(f"probe COMPUTE stage hung ({r['detail']}).")
            log("WORKER COMPUTE-WEDGED: the worker answers PJRT init but "
                "wedges on the first compile/execute — observed "
                "recoveries take hours, not minutes; treat the "
                "accelerator as down and use the CPU fallback until a "
                "probe goes fully healthy (`deppy doctor --watch`).")
            return 4
        log(f"probe hung ({r['detail']}).")
        holders = _chip_holders()
        if holders:
            log("other python processes that may hold the chip:")
            for h in holders[:8]:
                log(f"  {h}")
            log("if one of these is a stale run, terminate it and re-probe.")
        if attempt < retries:
            log(f"a crashed worker restarts in ~1-3 min; waiting "
                f"{retry_delay}s before the next probe...")
            time.sleep(retry_delay)
    log(f"WORKER RESTART SUSPECTED: {hangs}/{retries} probes hung. "
        "A crashed/restarting TPU worker blocks PJRT init for minutes; "
        "wait and re-run, and keep per-dispatch lane counts bounded "
        "(DEPPY_TPU_MAX_LANES) so programs do not crash it again.")
    return 1


def watch(interval: int = 600, probe_timeout: int = 120,
          log_path: str = "", until_healthy: bool = False,
          terminal_consecutive: int = 3) -> int:
    """Periodic health monitor: one compute probe per tick, one JSON line
    per result appended to ``log_path`` (and echoed to stderr).  With
    ``until_healthy`` the loop exits 0 at the first fully healthy probe —
    the building block for scripts that wait out a worker outage before
    launching accelerator work (`deppy doctor --watch --until-healthy &&
    make bench`) — and exits with :func:`diagnose`'s code on a status
    waiting cannot heal (no accelerator configured: 3, plugin/config
    failure: 2).  Hang statuses keep waiting; outlasting them is the
    point of the mode.

    Terminal statuses (error / cpu-only) must accumulate
    ``terminal_consecutive`` probes IN A ROW before the loop gives up:
    during a worker flap a single probe can crash (rc!=0 → "error") or
    catch jax mid-fallback-to-CPU ("cpu-only"), and a mode whose whole
    purpose is outlasting instability must not abort on one bad sample.
    The streak is over terminal-ness, not the exact status — a broken
    plugin that alternates error/cpu-only must still terminate (the
    exit code follows the last probe) — and any non-terminal probe
    (hang, compute-hang: the worker exists and may heal) resets it."""
    import json

    terminal_streak = 0
    while True:
        r = _probe(probe_timeout)
        rec = {"ts": round(time.time(), 1), **r}
        line = json.dumps(rec)
        print(line, file=sys.stderr, flush=True)
        if log_path:
            with open(log_path, "a") as f:
                f.write(line + "\n")
        if until_healthy:
            if r["status"] == "ok":
                return 0
            if r["status"] in ("cpu-only", "error"):
                terminal_streak += 1
                if terminal_streak >= terminal_consecutive:
                    return 3 if r["status"] == "cpu-only" else 2
            else:
                terminal_streak = 0
        time.sleep(interval)


def add_doctor_args(ap: argparse.ArgumentParser) -> None:
    """The doctor's flags, shared by this module's CLI and ``deppy
    doctor`` (cli.py) so defaults live in exactly one place — the
    :func:`diagnose` signature."""
    import inspect

    d = {
        k: p.default
        for k, p in inspect.signature(diagnose).parameters.items()
    }
    ap.add_argument("--probe-timeout", type=int, default=d["probe_timeout"])
    ap.add_argument("--retries", type=int, default=d["retries"])
    ap.add_argument("--retry-delay", type=int, default=d["retry_delay"])
    w = {
        k: p.default for k, p in inspect.signature(watch).parameters.items()
    }
    ap.add_argument("--watch", action="store_true",
                    help="loop forever (or until --until-healthy) probing "
                    "every --interval seconds, one JSON line per probe")
    ap.add_argument("--interval", type=int, default=w["interval"])
    ap.add_argument("--log", default=w["log_path"],
                    help="append watch-mode JSON lines to this file")
    ap.add_argument("--until-healthy", action="store_true",
                    help="watch mode exits 0 at the first healthy probe")
    ap.add_argument("--terminal-consecutive", type=int,
                    default=w["terminal_consecutive"],
                    help="watch mode gives up on error/cpu-only only "
                    "after this many consecutive probes agree (1 "
                    "restores fail-fast)")


def run_from_args(args) -> int:
    """Dispatch parsed doctor args (shared by ``deppy doctor`` and the
    module CLI)."""
    if getattr(args, "watch", False):
        return watch(args.interval, args.probe_timeout, args.log,
                     args.until_healthy, args.terminal_consecutive)
    return diagnose(args.probe_timeout, args.retries, args.retry_delay)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    add_doctor_args(ap)
    sys.exit(run_from_args(ap.parse_args()))


if __name__ == "__main__":
    main()
