"""TPU backend diagnostics: root-cause a hanging/failing accelerator init.

Rounds 1-2 of this build lost every TPU measurement to an "init hang" no
one could explain.  Round 3 root-caused it (see BASELINE.md TPU notes):

  * programs with too many vmap lanes reproducibly crash the tunneled
    worker (the engine now chunks dispatches, driver.MAX_LANES), and so
    do minutes-long single program executions (the engine now host-routes
    giant-problem core extraction, driver.HOST_CORE_NCONS);
  * a crashed worker then makes PJRT init HANG for minutes while it
    restarts — so "init hangs" is usually "worker is restarting", and the
    right response is a bounded wait + retry, not a fast fallback;
  * killing a probe mid-init can wedge the client side too, so probes must
    run in disposable subprocesses.

This module packages those findings as a tool: ``python -m
deppy_tpu.utils.tpu_doctor`` probes the backend in a subprocess with a
timeout, classifies the outcome (healthy / worker-restarting / plugin
failure / no accelerator), reports suspicious sibling processes that may
be holding the chip, and exits 0 only on a healthy accelerator.  bench.py
embeds the same retry logic; this is the standalone "why is my TPU not
answering" entry point.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

# The probe re-asserts JAX_PLATFORMS from the environment (the baked
# sitecustomize pins the platform selection otherwise — see
# utils/platform_env.py), so `JAX_PLATFORMS=cpu` correctly diagnoses
# "no accelerator" instead of hanging on the pinned TPU plugin.
PROBE_SRC = (
    "import os, time, jax; "
    "p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); "
    "t0=time.time(); d=jax.devices(); "
    "print(jax.default_backend(), len(d), round(time.time()-t0, 1))"
)


def _probe(timeout_s: int) -> dict:
    """One subprocess probe.  Returns {status, backend?, init_s?, detail}.

    Uses :func:`platform_env.run_captured` so a wedged runtime helper
    holding the pipes cannot re-hang the doctor past its own timeout."""
    from .platform_env import run_captured

    t0 = time.time()
    try:
        rc, stdout, stderr = run_captured(
            [sys.executable, "-c", PROBE_SRC], timeout_s=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {"status": "hang", "detail": f"init exceeded {timeout_s}s"}
    wall = time.time() - t0
    if rc != 0:
        tail = (stderr or "").strip().splitlines()[-3:]
        return {"status": "error", "detail": " | ".join(tail)}
    parts = (stdout or "").strip().split()
    backend = parts[0] if parts else "?"
    return {
        "status": "ok" if backend not in ("cpu", "?") else "cpu-only",
        "backend": backend,
        "init_s": round(wall, 1),
        "detail": stdout.strip(),
    }


def _chip_holders() -> list:
    """Best-effort list of other python processes that might hold the chip
    (a held chip makes init fail or hang until they exit)."""
    me = os.getpid()
    holders = []
    try:
        out = subprocess.run(
            ["pgrep", "-af", "python"], capture_output=True, text=True,
            timeout=10,
        )
        for line in (out.stdout or "").splitlines():
            pid_s, _, cmd = line.partition(" ")
            if "tpu_doctor" in cmd:  # ourselves / our parent shell
                continue
            if pid_s.isdigit() and int(pid_s) != me and (
                "jax" in cmd or "deppy" in cmd or "bench" in cmd
            ):
                # Truncate: agent/driver wrappers can carry multi-KB
                # command lines, and the report only needs the gist.
                cmd = cmd.strip()
                if len(cmd) > 160:
                    cmd = cmd[:160] + " ...[truncated]"
                holders.append(f"{pid_s} {cmd}")
    except (OSError, subprocess.TimeoutExpired):
        pass
    return holders


def diagnose(probe_timeout: int = 120, retries: int = 3,
             retry_delay: int = 90) -> int:
    """Run the diagnosis; prints a human report to stderr, returns an exit
    code: 0 healthy accelerator, 1 worker-restart suspected (retry later),
    2 plugin/config failure, 3 no accelerator configured."""
    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    plat = os.environ.get("JAX_PLATFORMS", "(unset)")
    log(f"JAX_PLATFORMS={plat}")
    hangs = 0
    for attempt in range(1, retries + 1):
        log(f"probe {attempt}/{retries} (timeout {probe_timeout}s)...")
        r = _probe(probe_timeout)
        if r["status"] == "ok":
            log(f"HEALTHY: backend={r['backend']} init={r['init_s']}s "
                f"({r['detail']})")
            return 0
        if r["status"] == "cpu-only":
            log("NO ACCELERATOR: jax resolved to the CPU backend — either "
                "JAX_PLATFORMS pins cpu or no TPU plugin is registered.")
            return 3
        if r["status"] == "error":
            log(f"PLUGIN FAILURE: probe crashed: {r['detail']}")
            log("Likely a config/env problem, not a busy worker; fix the "
                "plugin before retrying.")
            return 2
        hangs += 1
        log(f"probe hung ({r['detail']}).")
        holders = _chip_holders()
        if holders:
            log("other python processes that may hold the chip:")
            for h in holders[:8]:
                log(f"  {h}")
            log("if one of these is a stale run, terminate it and re-probe.")
        if attempt < retries:
            log(f"a crashed worker restarts in ~1-3 min; waiting "
                f"{retry_delay}s before the next probe...")
            time.sleep(retry_delay)
    log(f"WORKER RESTART SUSPECTED: {hangs}/{retries} probes hung. "
        "A crashed/restarting TPU worker blocks PJRT init for minutes; "
        "wait and re-run, and keep per-dispatch lane counts bounded "
        "(DEPPY_TPU_MAX_LANES) so programs do not crash it again.")
    return 1


def add_doctor_args(ap: argparse.ArgumentParser) -> None:
    """The doctor's flags, shared by this module's CLI and ``deppy
    doctor`` (cli.py) so defaults live in exactly one place — the
    :func:`diagnose` signature."""
    import inspect

    d = {
        k: p.default
        for k, p in inspect.signature(diagnose).parameters.items()
    }
    ap.add_argument("--probe-timeout", type=int, default=d["probe_timeout"])
    ap.add_argument("--retries", type=int, default=d["retries"])
    ap.add_argument("--retry-delay", type=int, default=d["retry_delay"])


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    add_doctor_args(ap)
    args = ap.parse_args()
    sys.exit(diagnose(args.probe_timeout, args.retries, args.retry_delay))


if __name__ == "__main__":
    main()
