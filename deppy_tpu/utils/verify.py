"""Independent solution checker.

Validates a proposed installed set against the constraint semantics defined
by the reference (constraints.go:72-75,96-102,133-140,160-165,196-204)
without involving any solver machinery — used as the oracle in fuzz and
differential tests.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Set

from ..sat.constraints import (
    AppliedConstraint,
    AtMost,
    Conflict,
    Dependency,
    Mandatory,
    Prohibited,
    Variable,
)


def check_solution(
    variables: Sequence[Variable], installed: Iterable[str]
) -> List[AppliedConstraint]:
    """Return the applied constraints violated by ``installed`` (empty list
    means the solution is valid)."""
    chosen: Set[str] = set(installed)
    violations: List[AppliedConstraint] = []
    for v in variables:
        for con in v.constraints:
            ok = True
            if isinstance(con, Mandatory):
                ok = v.identifier in chosen
            elif isinstance(con, Prohibited):
                ok = v.identifier not in chosen
            elif isinstance(con, Dependency):
                ok = v.identifier not in chosen or any(d in chosen for d in con.ids)
            elif isinstance(con, Conflict):
                ok = not (v.identifier in chosen and con.id in chosen)
            elif isinstance(con, AtMost):
                ok = sum(1 for d in con.ids if d in chosen) <= con.n
            if not ok:
                violations.append(AppliedConstraint(v, con))
    return violations
