"""Command-line interface.

The reference CLI is an empty cobra root command — "deppy, the open-source
constraint solver framework" with zero subcommands
(/root/reference/cmd/root/root.go:7-14, cmd/main.go:10-16).  SURVEY.md §3.3
directs the rebuild to make it real:

  * ``deppy resolve FILE``  — read a problem (or batch) file, print each
    Solution or the NotSatisfiable conflict set;
  * ``deppy bench``         — run the headline benchmark and print its one
    JSON line;
  * ``deppy serve``         — run the batch-resolution service (the analog
    of the reference's controller manager, main.go:46-86);
  * ``deppy stats``         — summarize a telemetry JSONL file (spans +
    last solve report; docs/observability.md).

Exit codes: 0 = all problems satisfiable, 1 = at least one unsatisfiable,
2 = bad input / usage, 3 = incomplete (iteration budget exhausted before a
definitive answer — the reference's ErrIncomplete, solve.go:14).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import io as problem_io
from .sat.errors import (BackendCapabilityError, DuplicateIdentifier,
                         InternalSolverError)


def _mesh_devices_arg(raw: str) -> int:
    """--mesh-devices value: a device count, or 'all' → -1 (every local
    device) — the same spelling DEPPY_TPU_MESH_DEVICES accepts."""
    if raw.strip().lower() == "all":
        return -1
    try:
        return int(raw)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected an integer or 'all', got {raw!r}")


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="deppy",
        description="deppy-tpu: an open-source constraint solver framework, "
        "TPU-native rebuild",
    )
    sub = parser.add_subparsers(dest="command")

    p_resolve = sub.add_parser(
        "resolve", help="resolve a problem file and print the solution(s)"
    )
    p_resolve.add_argument("file", help="JSON problem file (see deppy_tpu.io)")
    p_resolve.add_argument(
        "--backend",
        choices=["auto", "host", "tpu"],
        default="auto",
        help="solver backend (default: auto — tensor engine when a JAX "
        "device is usable, else the host engine)",
    )
    p_resolve.add_argument(
        "--output",
        choices=["text", "json"],
        default="text",
        help="output format (default: text)",
    )
    p_resolve.add_argument(
        "--max-steps",
        type=int,
        default=None,
        help="iteration budget per problem; exceeding it reports incomplete",
    )
    p_resolve.add_argument(
        "--checkpoint-dir",
        default=None,
        metavar="DIR",
        help="persist each dispatch group's results under DIR and resume "
        "a crashed batch run from its completed groups (tensor backend; "
        "see deppy_tpu.engine.checkpoint)",
    )
    p_resolve.add_argument(
        "--telemetry-file",
        default=None,
        metavar="FILE",
        help="append every pipeline span and the per-batch solve report "
        "as JSONL events to FILE (also via DEPPY_TPU_TELEMETRY_FILE; "
        "summarize with `deppy stats FILE`)",
    )
    p_resolve.add_argument(
        "--report",
        action="store_true",
        help="print the per-batch solve report (padding occupancy, "
        "escalation stage, host fallback) on stderr after resolving",
    )
    p_resolve.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="wall-clock budget for the whole resolve: problems not "
        "dispatched before it expires report incomplete instead of the "
        "batch aborting (also via DEPPY_TPU_BATCH_DEADLINE_S)",
    )
    p_resolve.add_argument(
        "--fault-plan",
        default=None,
        metavar="SPEC",
        help="arm the fault-injection harness: inline JSON, @FILE, or a "
        "path to a JSON fault plan (also via DEPPY_TPU_FAULT_PLAN; see "
        "docs/robustness.md)",
    )
    p_resolve.add_argument(
        "--host-workers", type=int, default=None, metavar="N",
        help="host-engine worker pool size for host-path solves "
        "(default min(cpu_count, 8); 0 = inline serial engine; also "
        "via DEPPY_TPU_HOST_WORKERS — see docs/robustness.md)",
    )

    p_bench = sub.add_parser(
        "bench", help="run the headline benchmark (one JSON line on stdout)"
    )
    p_bench.add_argument("--problems", type=int, default=4096)
    p_bench.add_argument("--length", type=int, default=48)

    p_serve = sub.add_parser(
        "serve", help="run the batch-resolution service"
    )
    # Serve flags default to None (sentinel) so precedence layers cleanly:
    # built-in defaults < --config file values < explicitly passed flags.
    p_serve.add_argument(
        "--bind-address", default=None,
        help="API + metrics listen address (reference main.go:48-49 "
        "metrics-bind-address; default :8080)",
    )
    p_serve.add_argument(
        "--health-probe-bind-address", default=None,
        help="healthz/readyz listen address (reference main.go:50; "
        "default :8081)",
    )
    p_serve.add_argument(
        "--backend", choices=["auto", "host", "tpu"], default=None
    )
    p_serve.add_argument("--max-steps", type=int, default=None)
    p_serve.add_argument(
        "--config", default=None, metavar="FILE",
        help="ResolverConfig file (the analog of the reference's "
        "controller_manager_config.yaml, config/manager/"
        "controller_manager_config.yaml:1-11); explicitly passed flags "
        "override file values",
    )
    p_serve.add_argument(
        "--telemetry-file", default=None, metavar="FILE",
        help="append every pipeline span and per-batch solve report as "
        "JSONL events to FILE (also via DEPPY_TPU_TELEMETRY_FILE)",
    )
    p_serve.add_argument(
        "--request-deadline", type=float, default=None, metavar="SECONDS",
        help="default wall-clock budget per /v1/resolve request; clients "
        "override with the X-Deppy-Deadline-S header (also via "
        "DEPPY_TPU_REQUEST_DEADLINE_S)",
    )
    p_serve.add_argument(
        "--fault-plan", default=None, metavar="SPEC",
        help="arm the fault-injection harness for the whole service "
        "(inline JSON, @FILE, or a path; also via DEPPY_TPU_FAULT_PLAN)",
    )
    p_serve.add_argument(
        "--sched", choices=["on", "off"], default=None,
        help="cross-request continuous-batching scheduler (default on; "
        "also via DEPPY_TPU_SCHED).  'off' restores per-request "
        "dispatch — responses are byte-identical either way",
    )
    p_serve.add_argument(
        "--sched-max-wait-ms", type=float, default=None, metavar="MS",
        help="scheduler flush policy: max milliseconds a queued problem "
        "waits for batchmates before dispatching (default 5; also via "
        "DEPPY_TPU_SCHED_MAX_WAIT_MS) — a lone request keeps low "
        "latency, a burst coalesces",
    )
    p_serve.add_argument(
        "--sched-max-fill", type=int, default=None, metavar="N",
        help="scheduler flush policy: dispatch as soon as a size class "
        "has N problems queued (default 256; also via "
        "DEPPY_TPU_SCHED_MAX_FILL)",
    )
    p_serve.add_argument(
        "--cache-size", type=int, default=None, metavar="N",
        help="canonical-form result-cache capacity in entries (default "
        "1024, 0 disables; also via DEPPY_TPU_CACHE_SIZE) — repeated "
        "identical problems are answered without a dispatch",
    )
    p_serve.add_argument(
        "--host-workers", type=int, default=None, metavar="N",
        help="host-engine worker pool size for breaker-open / "
        "host-backend serving (default min(cpu_count, 8); 0 = inline "
        "serial engine; also via DEPPY_TPU_HOST_WORKERS)",
    )
    p_serve.add_argument(
        "--incremental", choices=["on", "off"], default=None,
        help="delta-aware incremental resolution tier (default on; "
        "also via DEPPY_TPU_INCREMENTAL).  'off' removes the clause-set "
        "index and warm-start lane class, restoring pre-tier dispatch "
        "byte for byte",
    )
    p_serve.add_argument(
        "--incremental-max-delta", type=float, default=None,
        metavar="RATIO",
        help="touched-cone cutoff for warm starts: a delta whose cone "
        "covers more than this fraction of the problem's variables "
        "cold-solves instead (default 0.25; also via "
        "DEPPY_TPU_INCREMENTAL_MAX_DELTA)",
    )
    p_serve.add_argument(
        "--incremental-index-size", type=int, default=None, metavar="N",
        help="clause-set index capacity in entries (default 512, 0 "
        "disables the tier; also via DEPPY_TPU_INCREMENTAL_INDEX_SIZE)",
    )
    p_serve.add_argument(
        "--portfolio", choices=["auto", "on", "off"], default=None,
        help="portfolio engine racing (ISSUE 13): race the top-K "
        "candidate backends per cold flush and serve the first "
        "definitive finisher, cross-checked by sampled differential "
        "comparison (default auto — race only size classes with a "
        "measured `portfolio` row; 'off' restores single-backend "
        "dispatch byte for byte; also via DEPPY_TPU_PORTFOLIO)",
    )
    p_serve.add_argument(
        "--speculate", choices=["on", "off"], default=None,
        help="speculative pre-resolution (ISSUE 14): catalog publishes "
        "(POST /v1/catalog/publish / `deppy publish`) pre-solve "
        "affected cached families at idle priority and the what-if "
        "preview endpoint serves proposed-change resolutions read-only "
        "(default on; 'off' restores pre-change dispatch byte for byte "
        "and 404s both endpoints; also via DEPPY_TPU_SPECULATE)",
    )
    p_serve.add_argument(
        "--speculate-max-backlog", type=int, default=None, metavar="N",
        help="speculative pre-solve backlog cap in lanes — pre-solves "
        "past it are dropped and counted (default 2048; also via "
        "DEPPY_TPU_SPECULATE_MAX_BACKLOG)",
    )
    p_serve.add_argument(
        "--slo", default=None, metavar="SPEC",
        help="declarative per-tenant SLO config: inline JSON, @FILE, "
        "or a path mapping tenant -> {target_p99_s, error_budget} "
        "(also via DEPPY_TPU_SLO); burn rates ride /metrics and "
        "/debug/slo",
    )
    p_serve.add_argument(
        "--bcp",
        choices=["auto", "gather", "bits", "pallas", "blockwise",
                 "watched"],
        default=None,
        help="BCP propagation implementation (default auto — the "
        "measured-defaults registry, falling back to bits; also via "
        "DEPPY_TPU_BCP).  'watched' selects the compressed-clause-bank "
        "implication-driven engine (ISSUE 12)",
    )
    p_serve.add_argument(
        "--profile", choices=["on", "off"], default=None,
        help="engine cost profiler: per-dispatch trip ledger + "
        "per-backend cost attribution as `profile` sink events and "
        "deppy_profile_* metric families (default off; also via "
        "DEPPY_TPU_PROFILE; summarize with `deppy profile`)",
    )
    p_serve.add_argument(
        "--profile-sample", type=float, default=None, metavar="RATE",
        help="fraction of dispatches the armed profiler samples, in "
        "(0, 1] (default 1.0; also via DEPPY_TPU_PROFILE_SAMPLE) — "
        "bounds the armed overhead",
    )
    p_serve.add_argument(
        "--replica", default=None, metavar="ID",
        help="this replica's serving identity in a fleet (also via "
        "DEPPY_TPU_REPLICA): labels the per-tenant SLO families, "
        "/debug/slo, and the request root span so burn rate is "
        "attributable per tenant per replica",
    )
    p_serve.add_argument(
        "--sched-fair", choices=["on", "off"], default=None,
        help="weighted-fair per-tenant admission + priority lanes "
        "(default on; also via DEPPY_TPU_SCHED_FAIR).  'on' sheds "
        "each tenant at its weighted share of the queue instead of "
        "the global-depth 503 — one noisy tenant can no longer "
        "starve the rest at the door; 'off' restores the global "
        "gate byte for byte",
    )
    p_serve.add_argument(
        "--sched-tenant-weights", default=None, metavar="SPEC",
        help="tenant weights/priorities for the fair gate: inline "
        "JSON, @FILE, or a path mapping tenant -> weight number or "
        "{weight, priority} ('default' covers unlisted tenants; also "
        "via DEPPY_TPU_SCHED_TENANT_WEIGHTS)",
    )
    p_serve.add_argument(
        "--obs-stream", default=None, metavar="HOST:PORT",
        help="stream this replica's telemetry sink events to the fleet "
        "router's POST /fleet/telemetry aggregator at HOST:PORT "
        "(ISSUE 16; also via DEPPY_TPU_OBS_STREAM).  Batched and "
        "bounded: a slow aggregator drops batches (counted in "
        "deppy_obs_stream_dropped_total), never stalls serving",
    )
    p_serve.add_argument(
        "--obs-flush-ms", type=float, default=None, metavar="MS",
        help="telemetry-streamer flush interval in milliseconds "
        "(default 200; also via DEPPY_TPU_OBS_FLUSH_MS)",
    )
    p_serve.add_argument(
        "--obs-baseline", default=None, metavar="FILE",
        help="arm the cost-model drift watchdog against the committed "
        "baseline artifact (a BENCH_rNN.json with a costmodel section, "
        "or a `deppy profile --json` report; also via "
        "DEPPY_TPU_OBS_BASELINE).  Live us/trip per size class outside "
        "the band emits a costmodel_drift event and the "
        "deppy_costmodel_drift_ratio gauge",
    )
    p_serve.add_argument(
        "--fleet-router", default=None, metavar="HOST:PORT",
        help="announce this replica to the fleet router at HOST:PORT "
        "(ISSUE 17; also via DEPPY_TPU_FLEET_ROUTER): POST /fleet/join "
        "once serving starts — the router streams the warm state this "
        "replica's arcs inherit, then flips the ring atomically — and "
        "leave via the drain handoff on graceful shutdown",
    )
    p_serve.add_argument(
        "--fleet-advertise", default=None, metavar="HOST:PORT",
        help="the address this replica advertises when joining a fleet "
        "(default 127.0.0.1:<api-port>; also via "
        "DEPPY_TPU_FLEET_ADVERTISE)",
    )
    p_serve.add_argument(
        "--mesh-devices", type=_mesh_devices_arg, default=None,
        metavar="N|all",
        help="shard each coalesced micro-batch across N accelerator "
        "devices ('all' = every local device; default off — "
        "single-device dispatch; also via DEPPY_TPU_MESH_DEVICES).  "
        "Each device gets its own fault domain and "
        "deppy_breaker_state{device=...} breaker",
    )
    p_serve.add_argument(
        "--opt", choices=["on", "off"], default=None,
        help="optimization tier (ISSUE 18): best-solution queries — "
        "minimal-change upgrade planning, weighted soft constraints, "
        "and explain-why-not — behind POST /v1/optimize, served by a "
        "bound-tightening loop riding the scheduler's idle-priority "
        "queue (default on; 'off' 404s the endpoint and leaves "
        "/v1/resolve byte-identical; also via DEPPY_TPU_OPT)",
    )
    p_serve.add_argument(
        "--opt-max-iterations", type=int, default=None, metavar="N",
        help="optimization tier: cap on bound-tightening probes per "
        "request — past it the best model so far returns flagged "
        "non-optimal (default 64; also via "
        "DEPPY_TPU_OPT_MAX_ITERATIONS)",
    )
    p_serve.add_argument(
        "--opt-iter-budget", type=int, default=None, metavar="STEPS",
        help="optimization tier: engine step budget per tightening "
        "probe (default 1048576; also via DEPPY_TPU_OPT_ITER_BUDGET)",
    )
    p_serve.add_argument(
        "--opt-max-weight", type=int, default=None, metavar="W",
        help="optimization tier: largest accepted soft-constraint "
        "weight — bigger weights are a 400, bounding probe work "
        "(default 64; also via DEPPY_TPU_OPT_MAX_WEIGHT)",
    )
    p_serve.add_argument(
        "--route-learn", choices=["off", "observe", "on"], default=None,
        help="route-health plane (ISSUE 19): 'observe' arms the live "
        "regret ledger, measured-defaults staleness watcher, and "
        "idle-priority shadow probing of stale classes; 'on' adds the "
        "online route registry that adopts live-learned portfolio "
        "rows (racing order only — answers stay gated by the "
        "definitive-winner rule and sampled cross-check) and gossips "
        "them fleet-wide; default off arms nothing and keeps every "
        "surface byte-identical (also via DEPPY_TPU_ROUTE_LEARN; "
        "audit with `deppy routes`)",
    )
    p_serve.add_argument(
        "--route-shadow-rate", type=float, default=None, metavar="RATE",
        help="fraction of a stale-flagged class's flushes duplicated "
        "to one non-serving backend at idle priority (deterministic "
        "1-in-N per class, default 0.0625, 0 disables probing; also "
        "via DEPPY_TPU_ROUTE_SHADOW_RATE)",
    )
    p_serve.add_argument(
        "--route-registry", default=None, metavar="FILE",
        help="persist live-learned routing rows to FILE through the "
        "shared flock-guarded measured-defaults store, provenance-"
        "stamped (also via DEPPY_TPU_ROUTE_REGISTRY; default: "
        "in-memory only)",
    )
    p_serve.add_argument(
        "--sessions", choices=["on", "off"], default=None,
        help="stateful resolution sessions (ISSUE 20): POST "
        "/v1/session pins a catalog epoch server-side, then "
        "/v1/session/{id}/op drives assume/test/untest/resolve/"
        "explain against the retained state, answered byte-"
        "identically to a one-shot cold resolve; 'off' constructs "
        "none of it — the endpoints 404 and no session metric "
        "family registers (default on with the scheduler; also via "
        "DEPPY_TPU_SESSIONS)",
    )
    p_serve.add_argument(
        "--session-lease-s", type=float, default=None, metavar="SECONDS",
        help="session lease: each op renews; a session idle past its "
        "lease is swept and ops on it answer 404 (default 300; also "
        "via DEPPY_TPU_SESSION_LEASE_S)",
    )
    p_serve.add_argument(
        "--session-max", type=int, default=None, metavar="N",
        help="hard cap on live sessions per replica — creates beyond "
        "it evict an expired session or shed with a counted 503 "
        "(default 256; also via DEPPY_TPU_SESSION_MAX)",
    )
    p_serve.add_argument(
        "--session-max-per-tenant", type=int, default=None, metavar="N",
        help="per-tenant session cap, enforced before the replica-"
        "wide one (default 64; also via "
        "DEPPY_TPU_SESSION_MAX_PER_TENANT)",
    )

    p_route = sub.add_parser(
        "route",
        help="run the replica-fleet affinity router (ISSUE 15): a "
        "front-end speaking the /v1/resolve surface that routes each "
        "problem's family onto the consistent-hash ring so churn "
        "concentrates on the replica holding its warm seeds, health-"
        "probes replicas (a dead replica's arc reassigns, in-flight "
        "requests retry once on the successor), fans catalog "
        "publishes out fleet-wide, and orchestrates warm-state drain "
        "handoffs (POST /fleet/drain)",
    )
    p_route.add_argument(
        "--bind-address", default=":8079",
        help="router listen address (default :8079)",
    )
    p_route.add_argument(
        "--replicas", default=None, metavar="HOST:PORT[,...]",
        help="replica API addresses to front, comma-separated (also "
        "via DEPPY_TPU_FLEET_REPLICAS)",
    )
    p_route.add_argument(
        "--vnodes", type=int, default=None, metavar="N",
        help="virtual nodes per replica on the hash ring (default 64; "
        "also via DEPPY_TPU_FLEET_VNODES)",
    )
    p_route.add_argument(
        "--probe-interval", type=float, default=None, metavar="SECONDS",
        help="seconds between per-replica health probes (default 2; "
        "also via DEPPY_TPU_FLEET_PROBE_INTERVAL_S)",
    )
    p_route.add_argument(
        "--probe-failures", type=int, default=None, metavar="N",
        help="consecutive transport failures that mark a replica dead "
        "and reassign its arcs (default 3; also via "
        "DEPPY_TPU_FLEET_PROBE_FAILURES)",
    )
    p_route.add_argument(
        "--policy", choices=["affinity", "roundrobin"],
        default="affinity",
        help="routing policy (default affinity; roundrobin exists as "
        "the warm-state-destroying baseline for bench.py --workload "
        "fleet)",
    )
    p_route.add_argument(
        "--membership", choices=["elastic", "static"], default=None,
        help="fleet membership mode (default elastic; also via "
        "DEPPY_TPU_FLEET).  elastic arms runtime joins (POST "
        "/fleet/join — chunked warm-state streaming, then an atomic "
        "arc flip), drain-as-leave ring removal with a membership "
        "epoch, peer gossip, and GET /fleet/policy; static restores "
        "the PR 15 immutable-ring surface byte for byte",
    )
    p_route.add_argument(
        "--peers", default=None, metavar="HOST:PORT[,...]",
        help="peer router addresses for membership gossip, comma-"
        "separated (ISSUE 17; also via DEPPY_TPU_FLEET_PEERS): routers "
        "exchange epoch-versioned ring views over POST /fleet/sync so "
        "clients can hit any of them",
    )
    p_route.add_argument(
        "--telemetry-file", default=None, metavar="FILE",
        help="append router spans and fleet fault events as JSONL to "
        "FILE (also via DEPPY_TPU_TELEMETRY_FILE)",
    )
    p_route.add_argument(
        "--fault-plan", default=None, metavar="SPEC",
        help="arm the fault-injection harness for the router (the "
        "fleet.forward point; inline JSON, @FILE, or a path; also via "
        "DEPPY_TPU_FAULT_PLAN)",
    )
    p_route.add_argument(
        "--obs-sink", default=None, metavar="FILE",
        help="aggregate replica-streamed telemetry into the merged "
        "fleet JSONL sink at FILE (ISSUE 16; also via "
        "DEPPY_TPU_OBS_SINK).  Arms POST /fleet/telemetry, replica-"
        "stamps every event, and joins the router's own spans/events "
        "under replica=\"router\" — `deppy trace --fleet` reads this "
        "one file",
    )

    p_publish = sub.add_parser(
        "publish",
        help="publish a catalog delta to a running service "
        "(POST /v1/catalog/publish): the server invalidates retracted "
        "cache entries and pre-solves every affected cached family at "
        "idle priority, so dependents' re-asks become cache hits "
        "(ISSUE 14; --preview resolves the change read-only instead)",
    )
    p_publish.add_argument(
        "file",
        help="JSON publish document: {\"updates\": [{\"id\": ..., "
        "\"constraints\": [...]}], \"removed\": [...]} — constraint "
        "objects use the deppy_tpu.io problem-file format",
    )
    p_publish.add_argument(
        "--server", default="http://127.0.0.1:8080", metavar="URL",
        help="base URL of the running service (default "
        "http://127.0.0.1:8080)",
    )
    p_publish.add_argument(
        "--preview", action="store_true",
        help="POST /v1/resolve/preview instead: resolve the PROPOSED "
        "change against the live index without serving or caching it "
        "(upgrade-impact preview)",
    )
    p_publish.add_argument(
        "--limit", type=int, default=None, metavar="N",
        help="with --preview: cap the affected families previewed "
        "(most recently served first; server default 32)",
    )
    p_publish.add_argument(
        "--output", choices=["text", "json"], default="text",
        help="output format (default: text)",
    )

    p_optimize = sub.add_parser(
        "optimize",
        help="POST an optimize request to a running service "
        "(POST /v1/optimize, ISSUE 18): minimal-change upgrade "
        "planning (query 'upgrade') or weighted soft constraints "
        "(query 'soft'), answered optimal/degraded/unsat by the "
        "bound-tightening loop",
    )
    p_optimize.add_argument(
        "file",
        help="JSON optimize document: {\"query\": \"upgrade\"|\"soft\", "
        "\"variables\": [...], \"installed\": [...], \"prefer\": [...], "
        "\"soft\": [{\"id\", \"installed\", \"weight\"}]} — variables "
        "use the deppy_tpu.io problem-file format",
    )
    p_optimize.add_argument(
        "--server", default="http://127.0.0.1:8080", metavar="URL",
        help="base URL of the running service (default "
        "http://127.0.0.1:8080)",
    )
    p_optimize.add_argument(
        "--output", choices=["text", "json"], default="text",
        help="output format (default: text)",
    )

    p_explain = sub.add_parser(
        "explain",
        help="explain-why-not against a running service (ISSUE 18): "
        "POST /v1/optimize with query 'explain' — the named goals "
        "become mandatory and the answer is either a plan or the "
        "unsat core as a human-readable blocking set",
    )
    p_explain.add_argument(
        "file",
        help="JSON explain document: {\"variables\": [...], "
        "\"goal\": [ids...]} (a \"query\" field, if present, must be "
        "\"explain\")",
    )
    p_explain.add_argument(
        "--server", default="http://127.0.0.1:8080", metavar="URL",
        help="base URL of the running service (default "
        "http://127.0.0.1:8080)",
    )
    p_explain.add_argument(
        "--output", choices=["text", "json"], default="text",
        help="output format (default: text)",
    )

    p_stats = sub.add_parser(
        "stats",
        help="summarize a telemetry JSONL file: per-span counts/timings "
        "with p50/p95/p99 and the last solve report (see "
        "docs/observability.md)",
    )
    p_stats.add_argument(
        "file", nargs="?", default=None,
        help="telemetry JSONL file (default: $DEPPY_TPU_TELEMETRY_FILE)",
    )
    p_stats.add_argument(
        "--file", action="append", default=None, dest="files",
        metavar="FILE",
        help="additional telemetry JSONL file(s) to merge (repeatable; "
        "ISSUE 16): per-replica sinks summarize as one fleet view, "
        "with flight-recorder dump copies deduped by their per-process "
        "event seq",
    )
    p_stats.add_argument(
        "--output", choices=["text", "json"], default="text",
        help="output format (default: text)",
    )
    p_stats.add_argument(
        "--span", default=None, metavar="NAME",
        help="summarize only the named span (e.g. driver.solve)",
    )
    p_stats.add_argument(
        "--tenant", default=None, metavar="TENANT",
        help="summarize only events attributable to TENANT "
        "(X-Deppy-Tenant): spans whose attrs carry the tenant, "
        "deadline fault events, and single-tenant profile flushes "
        "(device dispatches and mixed-tenant flushes carry no tenant "
        "and are excluded)",
    )

    p_profile = sub.add_parser(
        "profile",
        help="render the engine cost model from a telemetry JSONL "
        "sink's `profile` events (armed via DEPPY_TPU_PROFILE=on): "
        "trip-overhead regression, useful-work ratio per size class, "
        "straggler/pad waste, per-backend us/solve — plus the "
        "portfolio race table (wins/cancels/win-margin per backend "
        "per size class, straggler resubmissions) from `race` events "
        "and the optimization-probe table (warm-vs-cold iterations, "
        "improvement deltas, per-probe backend wins; ISSUE 18) from "
        "`optimize` events (see docs/observability.md, Profiling)",
    )
    p_profile.add_argument(
        "file", nargs="?", default=None,
        help="telemetry JSONL file (default: $DEPPY_TPU_TELEMETRY_FILE)",
    )
    p_profile.add_argument(
        "--file", action="append", default=None, dest="files",
        metavar="FILE",
        help="additional telemetry JSONL file(s) to merge (repeatable; "
        "ISSUE 16): the cost model fits over every replica's profile "
        "events, dump copies deduped",
    )
    p_profile.add_argument(
        "--output", choices=["text", "json"], default="text",
        help="output format (default: text)",
    )

    p_routes = sub.add_parser(
        "routes",
        help="reconstruct the route-health table from a telemetry "
        "JSONL sink alone (ISSUE 19): per-size-class races, win "
        "shares, regret charged to the frozen default backend "
        "(censored-aware), staleness verdicts, shadow-probe counts, "
        "and live-learned row adoptions — the offline twin of the "
        "deppy_route_* metric families (see docs/observability.md, "
        "Route health)",
    )
    p_routes.add_argument(
        "file", nargs="?", default=None,
        help="telemetry JSONL file (default: $DEPPY_TPU_TELEMETRY_FILE)",
    )
    p_routes.add_argument(
        "--file", action="append", default=None, dest="files",
        metavar="FILE",
        help="additional telemetry JSONL file(s) to merge (repeatable): "
        "per-replica sinks reconstruct as one fleet route-health view, "
        "dump copies deduped",
    )
    p_routes.add_argument(
        "--registry", default=None, metavar="FILE",
        help="measured-defaults registry JSON to join provenance from "
        "(default: $DEPPY_TPU_MEASURED_DEFAULTS, else the package-"
        "local registry)",
    )
    p_routes.add_argument(
        "--output", choices=["text", "json"], default="text",
        help="output format (default: text)",
    )

    p_trace = sub.add_parser(
        "trace",
        help="reconstruct one request's span tree from a telemetry "
        "JSONL sink and pretty-print it (see docs/observability.md, "
        "Tracing)",
    )
    p_trace.add_argument(
        "trace_id",
        help="trace id or X-Deppy-Request-Id of the request",
    )
    p_trace.add_argument(
        "--file", action="append", default=None, metavar="FILE",
        help="telemetry JSONL file (repeatable — multiple replica "
        "sinks merge, dump copies deduped by event seq; default: "
        "$DEPPY_TPU_TELEMETRY_FILE)",
    )
    p_trace.add_argument(
        "--fleet", action="store_true",
        help="fleet mode (ISSUE 16): default the input to the merged "
        "fleet sink ($DEPPY_TPU_OBS_SINK, the router's --obs-sink "
        "file) and reconstruct the routed request as one tree — "
        "router hop + replica request + coalesced dispatch",
    )
    p_trace.add_argument(
        "--output", choices=["text", "json"], default="text",
        help="output format (default: text)",
    )

    p_top = sub.add_parser(
        "top",
        help="live terminal fleet dashboard (ISSUE 16): refreshes a "
        "per-replica table (state, warm-hit ratio, queue depth, worst "
        "cost-model drift ratio, telemetry events ingested) plus fleet "
        "rollups from the router's /fleet/metrics and /fleet/status",
    )
    p_top.add_argument(
        "--router", default="127.0.0.1:8079", metavar="HOST:PORT",
        help="fleet router address (default 127.0.0.1:8079)",
    )
    p_top.add_argument(
        "--interval", type=float, default=2.0, metavar="SECONDS",
        help="refresh interval (default 2)",
    )
    p_top.add_argument(
        "--once", action="store_true",
        help="render one snapshot and exit (no screen clearing) — the "
        "scriptable mode the obs smoke uses",
    )

    # Lint flags are declared inline (not imported from analysis.cli):
    # the parser is built for EVERY command, and a broken checker module
    # must only take down `deppy lint`, never `deppy serve`.
    p_lint = sub.add_parser(
        "lint",
        help="run the static-analysis checkers (trace-purity, "
        "concurrency-discipline, registry-sync, exception-hygiene, "
        "compile-surface, block-contract) and fail on findings not in "
        "analysis/baseline.json (see docs/analysis.md)",
    )
    p_lint.add_argument(
        "--checker", action="append", default=None, metavar="NAME",
        help="run only the named checker (repeatable; default: all of "
        "trace-purity, concurrency-discipline, registry-sync, "
        "exception-hygiene, compile-surface, block-contract)")
    p_lint.add_argument(
        "--changed", nargs="?", const="HEAD", default=None,
        metavar="BASE",
        help="fast mode: restrict the checkers to files changed vs "
        "BASE (`git diff --name-only BASE` + untracked; default "
        "HEAD).  Reverse-direction rules that must prove absence "
        "(unused knobs, stale fault points, flag mirrors) are skipped "
        "— run the full lint before merging (make lint-fast / make "
        "lint)")
    p_lint.add_argument(
        "--json", action="store_true",
        help="emit the findings (and the new-vs-baseline split) as one "
        "JSON document on stdout")
    p_lint.add_argument(
        "--github", action="store_true",
        help="emit ::warning workflow annotations for NEW findings "
        "(sanity CI)")
    p_lint.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="findings baseline (default: deppy_tpu/analysis/"
        "baseline.json)")
    p_lint.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report and fail on EVERY finding")
    p_lint.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current findings and exit 0 "
        "(burn-down bookkeeping; review the diff; with --checker, only "
        "that checker's keys are replaced)")
    p_lint.add_argument(
        "--strict-baseline", action="store_true",
        help="also fail when the baseline carries stale keys for "
        "findings that no longer exist (keeps burn-down honest)")

    p_compiles = sub.add_parser(
        "compiles",
        help="summarize compile-guard trace/retrace counts per jit "
        "entry from a telemetry JSONL sink (events recorded under "
        "DEPPY_TPU_COMPILE_GUARD=1; see docs/analysis.md), or print "
        "the static jit-surface registry with --surface",
    )
    p_compiles.add_argument(
        "file", nargs="?", default=None,
        help="telemetry JSONL file (default: $DEPPY_TPU_TELEMETRY_FILE)",
    )
    p_compiles.add_argument(
        "--output", choices=["text", "json"], default="text",
        help="output format (default: text)",
    )
    p_compiles.add_argument(
        "--surface", action="store_true",
        help="print the STATIC jit-surface registry (every jit/pjit/"
        "shard_map/pallas_call construction, with memo and "
        "compile-guard status) instead of reading a sink",
    )

    p_fleet = sub.add_parser(
        "fleet",
        help="elastic fleet operations against a running router "
        "(ISSUE 17): print the SLO-burn autoscale recommendation "
        "(GET /fleet/policy), and optionally apply it in local-process "
        "mode — execution stays operator-driven",
    )
    fleet_sub = p_fleet.add_subparsers(dest="fleet_command")
    p_fpolicy = fleet_sub.add_parser(
        "policy",
        help="print the router's current autoscale recommendation "
        "(scale_up / scale_down / rebalance / hold) as JSON",
    )
    p_fscale = fleet_sub.add_parser(
        "scale",
        help="fetch the recommendation; with --apply, execute it "
        "locally: scale_up spawns a `deppy serve --fleet-router` "
        "replica on a free port (it joins via the warm-state stream + "
        "arc flip), scale_down/rebalance drains the suggested replica",
    )
    for p_f in (p_fpolicy, p_fscale):
        p_f.add_argument(
            "--router", default="127.0.0.1:8079", metavar="HOST:PORT",
            help="fleet router address (default 127.0.0.1:8079)",
        )
    p_fscale.add_argument(
        "--apply", action="store_true",
        help="execute the recommendation in local-process mode (the "
        "bench/soak harness); without it the recommendation is only "
        "printed",
    )
    p_fscale.add_argument(
        "--backend", default="host",
        help="backend for a replica spawned by scale_up (default host)",
    )

    p_doctor = sub.add_parser(
        "doctor",
        help="diagnose the accelerator backend (probe in a killable "
        "subprocess, classify healthy / worker-restarting / plugin "
        "failure / no accelerator; exits 0 only on healthy)",
    )
    from .utils.tpu_doctor import add_doctor_args

    add_doctor_args(p_doctor)
    return parser


# ResolverConfig file keys → serve() kwargs (config/manager/
# resolver_config.yaml).  Parsed as YAML when available, JSON otherwise
# (the shipped config is valid YAML; JSON configs work without pyyaml).
_CONFIG_KEYS = {
    "bindAddress": ("bind_address", str),
    "healthProbeBindAddress": ("probe_address", str),
    "backend": ("backend", str),
    "maxSteps": ("max_steps", int),
    "requestDeadlineSeconds": ("request_deadline_s", float),
    "sched": ("sched", str),
    "schedMaxWaitMs": ("sched_max_wait_ms", float),
    "schedMaxFill": ("sched_max_fill", int),
    "cacheSize": ("cache_size", int),
    "hostWorkers": ("host_workers", int),
    "meshDevices": ("mesh_devices", int),
    "incremental": ("incremental", str),
    "incrementalMaxDelta": ("incremental_max_delta", float),
    "incrementalIndexSize": ("incremental_index_size", int),
    "slo": ("slo", str),
    "portfolio": ("portfolio", str),
    "speculate": ("speculate", str),
    "speculateMaxBacklog": ("speculate_max_backlog", int),
    "profile": ("profile", str),
    "profileSample": ("profile_sample", float),
    "bcp": ("bcp", str),
    "replica": ("replica", str),
    "schedFair": ("fair", str),
    "schedTenantWeights": ("tenant_weights", str),
    "obsStream": ("obs_stream", str),
    "obsFlushMs": ("obs_flush_ms", float),
    "obsBaseline": ("obs_baseline", str),
    "fleetRouter": ("fleet_router", str),
    "fleetAdvertise": ("fleet_advertise", str),
    "opt": ("opt", str),
    "optMaxIterations": ("opt_max_iterations", int),
    "optIterBudget": ("opt_iter_budget", int),
    "optMaxWeight": ("opt_max_weight", int),
    "routeLearn": ("route_learn", str),
    "routeShadowRate": ("route_shadow_rate", float),
    "routeRegistry": ("route_registry", str),
    "sessions": ("sessions", str),
    "sessionLeaseS": ("session_lease_s", float),
    "sessionMax": ("session_max", int),
    "sessionMaxPerTenant": ("session_max_per_tenant", int),
}


def _load_serve_config(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml

        try:
            doc = yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise problem_io.ProblemFormatError(
                f"config file {path}: invalid YAML: {e}"
            ) from e
    except ImportError:
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as e:
            raise problem_io.ProblemFormatError(
                f"config file {path}: invalid JSON: {e}"
            ) from e
    if not isinstance(doc, dict):
        raise problem_io.ProblemFormatError(
            f"config file {path}: expected a mapping, got {type(doc).__name__}"
        )
    out = {}
    for key, (kwarg, cast) in _CONFIG_KEYS.items():
        if key in doc and doc[key] is not None:
            out[kwarg] = cast(doc[key])
    return out


def _arm_fault_plan(spec) -> int:
    """Install a --fault-plan spec; returns 0 or a usage-error code.
    Rules naming no registered fault point warn (registry-sync): an
    operator chaos plan against a renamed point must not report green
    while injecting nothing."""
    if not spec:
        return 0
    from . import faults
    from .faults.inject import _warn_unmatched

    try:
        plan = faults.plan_from_spec(spec)
    except (OSError, ValueError) as e:
        print(f"error: invalid fault plan: {e}", file=sys.stderr)
        return 2
    _warn_unmatched(plan)
    faults.configure_plan(plan)
    return 0


def _cmd_resolve(args) -> int:
    if args.telemetry_file:
        from .telemetry import configure_sink

        configure_sink(args.telemetry_file)
    if _arm_fault_plan(args.fault_plan):
        return 2
    if args.host_workers is not None:
        from . import hostpool

        hostpool.configure_pool(args.host_workers)
    try:
        problems, is_batch = problem_io.load_document(args.file)
    except FileNotFoundError:
        print(f"error: no such file: {args.file}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read {args.file}: {e}", file=sys.stderr)
        return 2
    except problem_io.ProblemFormatError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    from .resolution.facade import BatchResolver

    resolver = BatchResolver(
        backend=args.backend, max_steps=args.max_steps,
        checkpoint_dir=args.checkpoint_dir, deadline_s=args.deadline,
    )
    try:
        results = resolver.solve(problems)
    except (BackendCapabilityError, DuplicateIdentifier,
            InternalSolverError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.report and resolver.last_report is not None:
        print(resolver.last_report.format_table(), file=sys.stderr)

    rendered = [problem_io.result_to_dict(res) for res in results]
    statuses = {r["status"] for r in rendered}
    rc = 3 if "incomplete" in statuses else (1 if "unsat" in statuses else 0)

    if args.output == "json":
        # Output shape is a function of the *input* form: a batch document
        # always yields {"results": [...]}, a single problem a bare object.
        doc = {"results": rendered} if is_batch else rendered[0]
        json.dump(doc, sys.stdout, indent=2)
        print()
        return rc

    for i, r in enumerate(rendered):
        prefix = f"problem {i}: " if is_batch else ""
        if r["status"] == "sat":
            sel = ", ".join(r["selected"]) if r["selected"] else "(nothing)"
            print(f"{prefix}resolution set: {sel}")
        elif r["status"] == "unsat":
            print(f"{prefix}constraints not satisfiable: "
                  + ", ".join(r["conflicts"]))
        else:
            print(f"{prefix}resolution incomplete: {r['error']}")
    return rc


def _cmd_route(args) -> int:
    """Run the replica-fleet affinity router (ISSUE 15)."""
    if args.telemetry_file:
        from .telemetry import configure_sink

        configure_sink(args.telemetry_file)
    if _arm_fault_plan(args.fault_plan):
        return 2
    from .fleet.router import serve_router

    try:
        serve_router(bind_address=args.bind_address,
                     replicas=args.replicas,
                     vnodes=args.vnodes,
                     probe_interval_s=args.probe_interval,
                     probe_failures=args.probe_failures,
                     policy=args.policy,
                     obs_sink=args.obs_sink,
                     membership=args.membership,
                     peers=args.peers)
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


def _cmd_fleet(args) -> int:
    """Elastic fleet operations (ISSUE 17): `deppy fleet policy`
    prints the router's autoscale recommendation; `deppy fleet scale
    --apply` executes it in local-process mode — scale_up spawns a
    joining replica, scale_down/rebalance drains the suggested victim.
    Exit 0 on success, 1 on a router-side error, 2 on usage/transport
    errors."""
    from http.client import HTTPConnection

    if not getattr(args, "fleet_command", None):
        print("error: fleet requires a subcommand (policy, scale)",
              file=sys.stderr)
        return 2
    host, _, port = args.router.rpartition(":")
    try:
        port_n = int(port)
    except ValueError:
        print(f"error: invalid --router address {args.router!r} "
              "(want HOST:PORT)", file=sys.stderr)
        return 2
    host = host or "127.0.0.1"

    def _exchange(method: str, path: str, doc=None, timeout=120.0):
        conn = HTTPConnection(host, port_n, timeout=timeout)
        try:
            conn.request(
                method, path,
                body=json.dumps(doc).encode() if doc is not None
                else None,
                headers={"Content-Type": "application/json"}
                if doc is not None else {})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    try:
        status, body = _exchange("GET", "/fleet/policy")
    except OSError as e:
        print(f"error: router {args.router} unreachable: {e}",
              file=sys.stderr)
        return 2
    if status != 200:
        print(f"error: GET /fleet/policy -> HTTP {status}: "
              f"{body[:200].decode('utf-8', 'replace')}",
              file=sys.stderr)
        return 1
    policy = json.loads(body).get("policy") or {}
    print(json.dumps(policy, indent=2))
    if args.fleet_command == "policy" or not args.apply:
        return 0
    decision = policy.get("decision")
    if decision == "hold":
        print("fleet scale: hold — nothing to apply")
        return 0
    if decision == "scale_up":
        import socket
        import subprocess

        ports = []
        for _ in range(2):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            s.close()
        addr = f"127.0.0.1:{ports[0]}"
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "import sys; from deppy_tpu.cli import main; "
             "sys.exit(main())",
             "serve", "--bind-address", addr,
             "--health-probe-bind-address", f"127.0.0.1:{ports[1]}",
             "--backend", args.backend,
             "--replica", f"scale{ports[0]}",
             "--fleet-router", args.router,
             "--fleet-advertise", addr],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        print(f"fleet scale: spawned replica {addr} (pid {proc.pid}); "
              "it joins the ring once its warm-state stream lands")
        return 0
    target = policy.get("target")
    if not target:
        print("fleet scale: recommendation names no target replica; "
              "nothing to apply")
        return 0
    try:
        status, body = _exchange("POST", "/fleet/drain",
                                 {"replica": target})
    except OSError as e:
        print(f"error: drain of {target} failed: {e}", file=sys.stderr)
        return 1
    if status != 200:
        print(f"error: POST /fleet/drain -> HTTP {status}: "
              f"{body[:200].decode('utf-8', 'replace')}",
              file=sys.stderr)
        return 1
    out = json.loads(body).get("drain") or {}
    print(f"fleet scale: drained {target} ({decision}); handed off "
          f"{out.get('handed_off', 0)} warm entries to "
          f"{sorted(out.get('recipients') or {})}")
    return 0


def _cmd_publish(args) -> int:
    """POST a catalog publish document to a running service — the
    subscribe-side CLI of the speculative tier (ISSUE 14).  With
    ``--preview`` the change resolves read-only instead (the what-if
    endpoint); exit 0 on a 2xx response, 2 on usage/transport errors,
    1 on any other HTTP status."""
    from http.client import HTTPConnection, HTTPSConnection
    from urllib.parse import urlsplit

    try:
        with open(args.file, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        print(f"error: no such file: {args.file}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read {args.file}: {e}", file=sys.stderr)
        return 2
    except json.JSONDecodeError as e:
        print(f"error: invalid JSON in {args.file}: {e}", file=sys.stderr)
        return 2
    if args.preview and args.limit is not None:
        if not isinstance(doc, dict):
            print("error: publish document must be a JSON object",
                  file=sys.stderr)
            return 2
        doc = dict(doc)
        doc["limit"] = args.limit
    parts = urlsplit(args.server if "://" in args.server
                     else f"http://{args.server}")
    if parts.scheme not in ("http", "https"):
        print(f"error: unsupported --server scheme {parts.scheme!r} "
              "(use http:// or https://)", file=sys.stderr)
        return 2
    path = "/v1/resolve/preview" if args.preview else "/v1/catalog/publish"
    conn_cls = HTTPSConnection if parts.scheme == "https" \
        else HTTPConnection
    default_port = 443 if parts.scheme == "https" else 8080
    try:
        conn = conn_cls(parts.hostname or "127.0.0.1",
                        parts.port or default_port, timeout=60)
        conn.request("POST", path, body=json.dumps(doc),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        status = resp.status
        conn.close()
    except OSError as e:
        print(f"error: cannot reach {args.server}: {e}", file=sys.stderr)
        return 2
    try:
        payload = json.loads(body)
    except (ValueError, json.JSONDecodeError):
        payload = {"raw": body.decode(errors="replace")}
    if args.output == "json" or status >= 400:
        json.dump(payload, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0 if status < 300 else (2 if status == 404 else 1)
    if args.preview:
        entries = payload.get("preview", [])
        print(f"preview: {len(entries)} affected famil"
              f"{'y' if len(entries) == 1 else 'ies'}")
        for e in entries:
            r = e.get("result") or {}
            status_s = r.get("status", e.get("error", "?"))
            detail = ""
            if status_s == "sat":
                sel = r.get("selected") or []
                detail = f"  selected: {', '.join(sel) or '(nothing)'}"
            elif status_s == "unsat":
                detail = f"  conflicts: {', '.join(r.get('conflicts', []))}"
            print(f"  {e.get('fingerprint', '?')[:12]}  "
                  f"[{e.get('delta_class') or 'cold'}]  {status_s}{detail}")
    else:
        p = payload.get("publish", {})
        print("published: "
              + "  ".join(f"{k}={p.get(k)}"
                          for k in ("changed", "affected", "invalidated",
                                    "queued", "dropped", "unchanged")))
    return 0


def _cmd_optimize(args, explain: bool = False) -> int:
    """POST an optimize document to a running service (POST
    /v1/optimize, ISSUE 18).  ``explain=True`` is the `deppy explain`
    spelling: the query field is forced to "explain" (a document that
    names a DIFFERENT query is a usage error, not silently rewritten).
    Exit 0 on a 2xx response, 2 on usage/transport errors (a 404 means
    the tier is off — DEPPY_TPU_OPT=off — or the server predates it),
    1 on any other HTTP status."""
    from http.client import HTTPConnection, HTTPSConnection
    from urllib.parse import urlsplit

    try:
        with open(args.file, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        print(f"error: no such file: {args.file}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read {args.file}: {e}", file=sys.stderr)
        return 2
    except json.JSONDecodeError as e:
        print(f"error: invalid JSON in {args.file}: {e}", file=sys.stderr)
        return 2
    if explain:
        if not isinstance(doc, dict):
            print("error: explain document must be a JSON object",
                  file=sys.stderr)
            return 2
        if doc.get("query", "explain") != "explain":
            print(f"error: `deppy explain` requires query \"explain\", "
                  f"the document says {doc['query']!r} — use "
                  "`deppy optimize`", file=sys.stderr)
            return 2
        doc = dict(doc)
        doc["query"] = "explain"
    parts = urlsplit(args.server if "://" in args.server
                     else f"http://{args.server}")
    if parts.scheme not in ("http", "https"):
        print(f"error: unsupported --server scheme {parts.scheme!r} "
              "(use http:// or https://)", file=sys.stderr)
        return 2
    conn_cls = HTTPSConnection if parts.scheme == "https" \
        else HTTPConnection
    default_port = 443 if parts.scheme == "https" else 8080
    try:
        conn = conn_cls(parts.hostname or "127.0.0.1",
                        parts.port or default_port, timeout=60)
        conn.request("POST", "/v1/optimize", body=json.dumps(doc),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        status = resp.status
        conn.close()
    except OSError as e:
        print(f"error: cannot reach {args.server}: {e}", file=sys.stderr)
        return 2
    try:
        payload = json.loads(body)
    except (ValueError, json.JSONDecodeError):
        payload = {"raw": body.decode(errors="replace")}
    if args.output == "json" or status >= 400:
        json.dump(payload, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0 if status < 300 else (2 if status == 404 else 1)
    out = payload.get("optimize", {})
    if out.get("query") == "explain":
        if out.get("status") == "feasible":
            plan = out.get("plan") or []
            print(f"feasible: {', '.join(plan) or '(nothing)'}")
        elif out.get("status") == "blocked":
            print("blocked:")
            for line in out.get("blocking", []):
                print(f"  {line}")
        else:
            print(f"degraded: {out.get('reason')}")
    else:
        head = out.get("status", "?")
        if head == "degraded":
            head += f" ({out.get('reason')})"
        elif out.get("proof"):
            head += f" (proof: {out['proof']})"
        print(f"{head}: objective={out.get('objective')} "
              f"iterations={out.get('iterations')} "
              f"improvements={out.get('improvements')}")
        if out.get("status") == "unsat":
            for line in out.get("blocking", []):
                print(f"  {line}")
        else:
            sel = out.get("selected") or []
            print(f"  selected: {', '.join(sel) or '(nothing)'}")
            if out.get("query") == "upgrade":
                print(f"  touched={out.get('touched')} "
                      f"missing_prefer="
                      f"{', '.join(out.get('missing_prefer') or []) or '-'}")
    return 0


def _cmd_bench(args) -> int:
    from .benchmarks import headline

    try:
        headline.run(n_problems=args.problems, length=args.length)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over pre-sorted values (0 on empty) —
    the shared telemetry statistic (one implementation for stats, the
    trip ledger, and the SLO window)."""
    from .telemetry import percentile

    return percentile(sorted_vals, q)


def _iter_sink_events(path: str):
    """Yield one item per non-empty sink line: the parsed event dict, or
    None for a malformed line (callers count those).  Lives in the
    telemetry package (the sink's read side); this is the CLI-local
    alias."""
    from .telemetry import iter_sink_events

    return iter_sink_events(path)


def _sink_paths(args) -> List[str]:
    """Effective sink path list for stats/profile: the positional file
    plus any repeated ``--file`` (ISSUE 16), falling back to
    $DEPPY_TPU_TELEMETRY_FILE when neither was given."""
    from . import config

    paths = ([args.file] if args.file else []) \
        + list(getattr(args, "files", None) or [])
    if not paths:
        default = config.env_raw("DEPPY_TPU_TELEMETRY_FILE")
        if default:
            paths = [default]
    return paths


def _iter_paths_events(paths: List[str]):
    """One path reads verbatim (byte-identical single-sink behavior);
    several merge with cross-replica dedupe (ISSUE 16)."""
    if len(paths) == 1:
        return _iter_sink_events(paths[0])
    from .telemetry import iter_merged_sink_events

    return iter_merged_sink_events(paths)


def _cmd_stats(args) -> int:
    """Summarize a telemetry JSONL file (the sink written under
    ``--telemetry-file`` / ``DEPPY_TPU_TELEMETRY_FILE``): per-span
    count/total/mean/p50/p95/p99 wall clock, event totals, and the last
    recorded solve report — the same report `deppy resolve --report`
    and the bench harness print.  ``--span NAME`` narrows the summary
    to one span family.  Repeated ``--file`` merges several replica
    sinks into one fleet summary (ISSUE 16)."""
    paths = _sink_paths(args)
    if not paths:
        print("error: no telemetry file (pass FILE or set "
              "DEPPY_TPU_TELEMETRY_FILE)", file=sys.stderr)
        return 2
    path = ", ".join(paths)
    spans: dict = {}
    last_report = None
    n_events = 0
    n_bad = 0
    kinds: dict = {}
    # Trip-ledger tally (ISSUE 11): `profile` events summarize inline
    # alongside the kind=n line; the full cost model is `deppy profile`.
    prof = {"events": 0, "trips": 0, "lane_steps": 0,
            "_useful": 0.0, "_useful_n": 0}
    try:
        for ev in _iter_paths_events(paths):
            if ev is None:
                n_bad += 1
                continue
            if args.tenant is not None:
                # --tenant: keep only events attributable to the tenant
                # — spans carrying it in attrs (the service.request
                # root), and fault/profile events stamped with it.
                if (ev.get("tenant") != args.tenant
                        and (ev.get("attrs") or {}).get("tenant")
                        != args.tenant):
                    continue
            n_events += 1
            if ev.get("kind") == "profile":
                prof["events"] += 1
                prof["trips"] += int(ev.get("trips", 0) or 0)
                prof["lane_steps"] += int(ev.get("lane_steps", 0) or 0)
                if ev.get("useful_work_ratio") is not None:
                    try:
                        prof["_useful"] += float(ev["useful_work_ratio"])
                        prof["_useful_n"] += 1
                    except (TypeError, ValueError):
                        pass
            kind = ev.get("kind", "?")
            kinds[kind] = kinds.get(kind, 0) + 1
            if ev.get("kind") == "span":
                name = ev.get("name", "?")
                if args.span is not None and name != args.span:
                    # Filter in the read loop: a --span run over a
                    # long-lived sink must not buffer every family's
                    # durations just to discard them afterwards.
                    continue
                agg = spans.setdefault(
                    name,
                    {"count": 0, "total_s": 0.0, "durs": []},
                )
                agg["count"] += 1
                try:
                    dur = float(ev.get("dur_s", 0.0))
                except (TypeError, ValueError):
                    continue
                agg["total_s"] += dur
                agg["durs"].append(dur)
            elif ev.get("kind") == "report":
                if isinstance(ev.get("report"), dict):
                    last_report = ev["report"]
    except FileNotFoundError:
        print(f"error: no such file: {path}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read {path}: {e}", file=sys.stderr)
        return 2

    for agg in spans.values():
        agg["mean_s"] = agg["total_s"] / agg["count"] if agg["count"] else 0.0
        durs = sorted(agg.pop("durs"))
        for q in (50, 95, 99):
            agg[f"p{q}_s"] = _percentile(durs, q)

    useful_n = prof.pop("_useful_n")
    useful_sum = prof.pop("_useful")
    prof["mean_useful_work_ratio"] = (
        round(useful_sum / useful_n, 4) if useful_n else None)

    if args.output == "json":
        json.dump({"events": n_events, "malformed_lines": n_bad,
                   "event_kinds": kinds,
                   "tenant": args.tenant,
                   "profile": (prof if prof["events"] else None),
                   "spans": spans,
                   # --span narrows to one span family in BOTH formats.
                   "last_report": (last_report if args.span is None
                                   else None)},
                  sys.stdout, indent=2, sort_keys=True)
        print()
        return 0

    print(f"telemetry: {n_events} events from {path}"
          + (f" (tenant {args.tenant})" if args.tenant else "")
          + (f" ({n_bad} malformed lines skipped)" if n_bad else ""))
    # Non-span kinds get a one-line tally so fault/breaker/lockdep
    # events are visible from `deppy stats` without a trace id in hand.
    other = {k: n for k, n in sorted(kinds.items())
             if k not in ("span", "report")}
    if other and args.span is None:
        print("events: " + "  ".join(f"{k}={n}"
                                     for k, n in other.items()))
    if prof["events"] and args.span is None:
        useful = prof["mean_useful_work_ratio"]
        print(f"profile: {prof['events']} events  "
              f"trips={prof['trips']}  lane_steps={prof['lane_steps']}"
              + (f"  useful={useful:.3f}" if useful is not None else "")
              + "  (full cost model: `deppy profile`)")
    if spans:
        width = max(len(n) for n in spans)
        print(f"{'span'.ljust(width)}  {'count':>7}  {'total_s':>9}  "
              f"{'mean_ms':>8}  {'p50_ms':>8}  {'p95_ms':>8}  "
              f"{'p99_ms':>8}")
        for name in sorted(spans):
            agg = spans[name]
            print(f"{name.ljust(width)}  {agg['count']:>7}  "
                  f"{agg['total_s']:>9.3f}  {agg['mean_s'] * 1e3:>8.2f}  "
                  f"{agg['p50_s'] * 1e3:>8.2f}  "
                  f"{agg['p95_s'] * 1e3:>8.2f}  "
                  f"{agg['p99_s'] * 1e3:>8.2f}")
    elif args.span is not None:
        print(f"no span events named {args.span!r}")
    else:
        print("no span events recorded")
    if last_report is not None and args.span is None:
        from .telemetry import SolveReport

        print()
        # One canonical renderer: the same table `deppy resolve
        # --report` and the bench harness print.
        print("last " + SolveReport.from_dict(last_report).format_table())
    return 0


def _cmd_trace(args) -> int:
    """Reconstruct one request's span tree from a telemetry JSONL sink
    (span/fault/breaker events stamped with trace ids, plus flight-
    recorder ``trace`` dumps) and pretty-print it — including dispatch
    traces grafted via their span links, so a request served by a
    coalesced dispatch shows queue-wait → dispatch (with retry/fallback
    events) → decode as one tree.  ``--fleet`` reads the merged fleet
    sink instead, so a routed request reconstructs router hop →
    replica request → dispatch from one file; repeated ``--file``
    merges several replica sinks with dump copies deduped
    (ISSUE 16)."""
    from . import config

    paths = list(args.file or [])
    if not paths:
        default = config.env_raw("DEPPY_TPU_OBS_SINK") if args.fleet \
            else config.env_raw("DEPPY_TPU_TELEMETRY_FILE")
        if default:
            paths = [default]
    if not paths:
        print("error: no telemetry file (pass --file or set "
              + ("DEPPY_TPU_OBS_SINK" if args.fleet
                 else "DEPPY_TPU_TELEMETRY_FILE") + ")", file=sys.stderr)
        return 2
    path = ", ".join(paths)

    # (trace_id, span_id) -> span event; trace_id -> [events]; the
    # request-id alias map comes from flight-recorder dumps.
    spans: dict = {}
    events_by_trace: dict = {}
    request_alias: dict = {}
    seen_events: set = set()

    def _take_span(ev):
        tid, sid = ev.get("trace_id"), ev.get("span_id")
        if tid and sid:
            spans[(tid, sid)] = ev
            # Root spans carry the request id in their attrs, so a
            # client-chosen X-Deppy-Request-Id resolves from live sink
            # lines alone (not just flight-recorder dumps).
            rid = (ev.get("attrs") or {}).get("request_id")
            if rid:
                request_alias.setdefault(rid, tid)

    def _take_event(ev):
        tid = ev.get("trace_id")
        if not tid:
            return
        # The same fault/breaker event reaches the sink twice when a
        # flight-recorder dump follows the live stamped line (and once
        # more per additional dump).  Stamped events carry a per-process
        # `seq` exactly so dump copies dedupe without collapsing
        # genuinely distinct identical-field events; pre-seq sink lines
        # fall back to the full canonical form.  seq counters are
        # per-process, so in a merged fleet sink the key needs the
        # aggregator's replica stamp too (absent = None on local sinks,
        # preserving single-sink behavior).
        seq = ev.get("seq")
        key = (ev.get("replica"), tid, seq) if seq is not None \
            else json.dumps(ev, sort_keys=True, default=str)
        if key in seen_events:
            return
        seen_events.add(key)
        events_by_trace.setdefault(tid, []).append(ev)

    try:
        for ev in _iter_paths_events(paths):
            if ev is None:
                continue
            kind = ev.get("kind")
            if kind == "span":
                _take_span(ev)
            elif kind == "trace" and isinstance(ev.get("trace"), dict):
                trace = ev["trace"]
                if trace.get("request_id") and trace.get("trace_id"):
                    request_alias[trace["request_id"]] = trace["trace_id"]
                for sp in trace.get("spans", []):
                    _take_span(sp)
                for fe in trace.get("events", []):
                    _take_event(fe)
            elif kind in ("fault", "breaker", "lockdep", "profile"):
                # `profile` events (ISSUE 11) are stamped like fault
                # events when a dispatch trace was active — the span
                # tree then shows the trip ledger of the dispatch that
                # served the request.
                _take_event(ev)
    except FileNotFoundError:
        print(f"error: no such file: {path}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read {path}: {e}", file=sys.stderr)
        return 2

    target = request_alias.get(args.trace_id, args.trace_id)
    # Pull in traces that LINK into the target (coalesced dispatches):
    # their root spans display under the linked request span.
    included = {target}
    graft = {}  # trace_id -> parent span_id to graft its roots under
    changed = True
    while changed:
        changed = False
        for (tid, sid), sp in spans.items():
            if tid in included:
                continue
            for link in sp.get("links") or []:
                if link.get("trace_id") in included:
                    included.add(tid)
                    graft[tid] = link.get("span_id")
                    changed = True
                    break

    chosen = [sp for (tid, _), sp in spans.items() if tid in included]
    if not chosen:
        print(f"error: no spans for trace {args.trace_id!r} in {path}",
              file=sys.stderr)
        return 2
    chosen.sort(key=lambda sp: sp.get("ts", 0.0))
    fault_events = [e for tid in included
                    for e in events_by_trace.get(tid, [])]

    if args.output == "json":
        json.dump({"trace_id": target, "spans": chosen,
                   "events": fault_events}, sys.stdout, indent=2,
                  default=str)
        print()
        return 0

    by_id = {sp["span_id"]: sp for sp in chosen}
    children: dict = {}
    roots = []
    for sp in chosen:
        parent = sp.get("parent_id")
        if sp["trace_id"] in graft and parent not in by_id:
            parent = graft[sp["trace_id"]]  # dispatch root → link target
        if parent in by_id:
            children.setdefault(parent, []).append(sp)
        else:
            roots.append(sp)
    notes: dict = {}
    for e in fault_events:
        notes.setdefault(e.get("parent_id"), []).append(e)

    def _fmt_span(sp):
        attrs = {k: v for k, v in (sp.get("attrs") or {}).items()}
        extra = ""
        if sp.get("links"):
            extra += "  links=" + ",".join(
                link.get("trace_id", "?")[:8] for link in sp["links"])
        if attrs:
            extra += "  " + " ".join(f"{k}={v}"
                                     for k, v in sorted(attrs.items()))
        return (f"{sp.get('name', '?')}  "
                f"{float(sp.get('dur_s', 0.0)) * 1e3:.2f}ms  "
                f"[{sp.get('span_id', '?')[:8]}]{extra}")

    def _fmt_event(e):
        detail = " ".join(
            f"{k}={v}" for k, v in sorted(e.items())
            if k not in ("ts", "kind", "trace_id", "parent_id"))
        return f"({e.get('kind')}) {detail}"

    def _walk(sp, prefix, is_last):
        branch = "└─ " if is_last else "├─ "
        print(prefix + branch + _fmt_span(sp))
        child_prefix = prefix + ("   " if is_last else "│  ")
        kids = children.get(sp["span_id"], [])
        tail = notes.get(sp["span_id"], [])
        items = [("span", k) for k in kids] + [("event", e) for e in tail]
        for i, (kind, item) in enumerate(items):
            last = i == len(items) - 1
            if kind == "span":
                _walk(item, child_prefix, last)
            else:
                print(child_prefix + ("└─ " if last else "├─ ")
                      + _fmt_event(item))

    print(f"trace {target}"
          + (f" (request {args.trace_id})"
             if args.trace_id != target else ""))
    for i, root in enumerate(roots):
        _walk(root, "", i == len(roots) - 1)
    # Events whose parent span never completed (process died mid-span,
    # or stamped with no open span) must not vanish from the text view
    # — the JSON view includes them, and an incident reconstruction is
    # exactly when they matter.
    orphans = [e for pid, evs in notes.items() if pid not in by_id
               for e in evs]
    if orphans:
        print("unattached events:")
        for i, e in enumerate(orphans):
            print(("└─ " if i == len(orphans) - 1 else "├─ ")
                  + _fmt_event(e))
    return 0


def _cmd_profile(args) -> int:
    """Render the engine cost model from a sink's ``profile`` events
    (ISSUE 11): trip-overhead regression, useful-work ratio per size
    class, straggler/pad waste breakdowns, per-backend µs/solve — the
    continuously-collected version of the hand-run A/B trip-overhead
    model (see docs/observability.md, Profiling).  Repeated ``--file``
    fits the model over several replica sinks merged (ISSUE 16)."""
    from .profile import report as profile_report

    paths = _sink_paths(args)
    if not paths:
        print("error: no telemetry file (pass FILE or set "
              "DEPPY_TPU_TELEMETRY_FILE)", file=sys.stderr)
        return 2
    path = ", ".join(paths)
    try:
        summary = profile_report.summarize(
            paths[0] if len(paths) == 1 else paths)
    except FileNotFoundError:
        print(f"error: no such file: {path}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read {path}: {e}", file=sys.stderr)
        return 2
    if args.output == "json":
        json.dump(summary, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    if not summary["profile_events"] and not summary.get("races"):
        print(f"no profile or race events in {path} (arm with "
              f"DEPPY_TPU_PROFILE=on and a telemetry sink; race events "
              f"ride every portfolio race)")
        return 0
    print(profile_report.render_text(summary, path))
    return 0


def _cmd_routes(args) -> int:
    """Reconstruct the route-health table (ISSUE 19) from the JSONL
    sink alone: the same :class:`RegretLedger` the live plane drives,
    replayed offline over ``race``/``route``/``route_stale``/
    ``route_learned`` events, joined with the measured-defaults
    registry's provenance stamps.  Repeated ``--file`` merges replica
    sinks into one fleet view."""
    from .engine import defaults_store
    from .routes import report as routes_report

    paths = _sink_paths(args)
    if not paths:
        print("error: no telemetry file (pass FILE or set "
              "DEPPY_TPU_TELEMETRY_FILE)", file=sys.stderr)
        return 2
    try:
        rows_doc = defaults_store.read_rows(args.registry)
    except OSError:
        rows_doc = {}
    try:
        doc = routes_report.build_report(_iter_paths_events(paths),
                                         rows_doc=rows_doc)
    except FileNotFoundError:
        print(f"error: no such file: {', '.join(paths)}",
              file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read {', '.join(paths)}: {e}",
              file=sys.stderr)
        return 2
    if args.output == "json":
        json.dump(doc, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    print(routes_report.render_text(doc))
    return 0


def _cmd_compiles(args) -> int:
    """Summarize ``compileguard`` events from a telemetry JSONL sink:
    per jit entry, total traces, distinct abstract signatures, retraces
    (traces beyond the first per signature), trace wall time, and any
    retrace-budget violations — the offline view of a compile storm.
    ``--surface`` instead prints the static jit-surface registry the
    ``compile-surface`` checker builds (no sink needed)."""
    if args.surface:
        from .analysis.compile_surface import jit_surface

        entries = jit_surface()
        if args.output == "json":
            json.dump({"entries": [e.to_dict() for e in entries]},
                      sys.stdout, indent=2)
            print()
            return 0
        width = max((len(f"{e.path}:{e.line}") for e in entries),
                    default=4)
        print(f"{'site'.ljust(width)}  {'kind'.ljust(11)}  "
              f"{'memo':>4}  {'guard':>5}  name")
        for e in entries:
            site = f"{e.path}:{e.line}"
            print(f"{site.ljust(width)}  {e.kind.ljust(11)}  "
                  f"{'yes' if e.memoized else '-':>4}  "
                  f"{'yes' if e.observed else '-':>5}  {e.name}")
        return 0

    from . import config

    path = args.file or config.env_raw("DEPPY_TPU_TELEMETRY_FILE")
    if not path:
        print("error: no telemetry file (pass FILE or set "
              "DEPPY_TPU_TELEMETRY_FILE)", file=sys.stderr)
        return 2
    per_entry: dict = {}
    violations = []
    try:
        for ev in _iter_sink_events(path):
            if ev is None or ev.get("kind") != "compileguard":
                continue
            entry = ev.get("entry", "?")
            agg = per_entry.setdefault(
                entry, {"traces": 0, "signatures": set(),
                        "retraces": 0, "trace_s": 0.0})
            if ev.get("violation"):
                violations.append(ev)
                continue
            agg["traces"] += 1
            sig = ev.get("signature")
            if sig in agg["signatures"]:
                agg["retraces"] += 1
            elif sig is not None:
                agg["signatures"].add(sig)
            try:
                agg["trace_s"] += float(ev.get("dur_s", 0.0))
            except (TypeError, ValueError):
                pass
    except FileNotFoundError:
        print(f"error: no such file: {path}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read {path}: {e}", file=sys.stderr)
        return 2

    for agg in per_entry.values():
        agg["signatures"] = len(agg["signatures"])

    if args.output == "json":
        json.dump({"entries": per_entry, "violations": violations},
                  sys.stdout, indent=2, sort_keys=True, default=str)
        print()
        return 0
    if not per_entry:
        print(f"no compileguard events in {path} (arm with "
              f"DEPPY_TPU_COMPILE_GUARD=1 and a telemetry sink)")
        return 0
    width = max(len(n) for n in per_entry)
    print(f"{'entry'.ljust(width)}  {'traces':>7}  {'sigs':>5}  "
          f"{'retraces':>8}  {'trace_s':>8}")
    for name in sorted(per_entry):
        agg = per_entry[name]
        print(f"{name.ljust(width)}  {agg['traces']:>7}  "
              f"{agg['signatures']:>5}  {agg['retraces']:>8}  "
              f"{agg['trace_s']:>8.3f}")
    for v in violations:
        print(f"VIOLATION {v.get('entry')}: signature traced "
              f"{v.get('n_trace')} times (budget {v.get('budget')}) "
              f"at {v.get('site')}")
    return 0


def _cmd_serve(args) -> int:
    from .service import serve

    if args.telemetry_file:
        from .telemetry import configure_sink

        configure_sink(args.telemetry_file)
    if _arm_fault_plan(args.fault_plan):
        return 2

    # Precedence: built-in defaults < --config file < explicit flags
    # (the reference's flag-vs-ControllerManagerConfig behavior).  Flags
    # default to None, so a non-None parsed value IS an explicit flag.
    kwargs = {
        "bind_address": ":8080",
        "probe_address": ":8081",
        "backend": "auto",
        "max_steps": None,
        "request_deadline_s": None,
        "sched": None,
        "sched_max_wait_ms": None,
        "sched_max_fill": None,
        "cache_size": None,
        "host_workers": None,
        "mesh_devices": None,
        "incremental": None,
        "incremental_max_delta": None,
        "incremental_index_size": None,
        "slo": None,
        "portfolio": None,
        "speculate": None,
        "speculate_max_backlog": None,
        "profile": None,
        "profile_sample": None,
        "bcp": None,
        "replica": None,
        "fair": None,
        "tenant_weights": None,
        "obs_stream": None,
        "obs_flush_ms": None,
        "obs_baseline": None,
        "fleet_router": None,
        "fleet_advertise": None,
        "opt": None,
        "opt_max_iterations": None,
        "opt_iter_budget": None,
        "opt_max_weight": None,
        "route_learn": None,
        "route_shadow_rate": None,
        "route_registry": None,
        "sessions": None,
        "session_lease_s": None,
        "session_max": None,
        "session_max_per_tenant": None,
    }
    try:
        if args.config:
            kwargs.update(_load_serve_config(args.config))
        for key, val in (
            ("bind_address", args.bind_address),
            ("probe_address", args.health_probe_bind_address),
            ("backend", args.backend),
            ("max_steps", args.max_steps),
            ("request_deadline_s", args.request_deadline),
            ("sched", args.sched),
            ("sched_max_wait_ms", args.sched_max_wait_ms),
            ("sched_max_fill", args.sched_max_fill),
            ("cache_size", args.cache_size),
            ("host_workers", args.host_workers),
            ("mesh_devices", args.mesh_devices),
            ("incremental", args.incremental),
            ("incremental_max_delta", args.incremental_max_delta),
            ("incremental_index_size", args.incremental_index_size),
            ("slo", args.slo),
            ("portfolio", args.portfolio),
            ("speculate", args.speculate),
            ("speculate_max_backlog", args.speculate_max_backlog),
            ("profile", args.profile),
            ("profile_sample", args.profile_sample),
            ("bcp", args.bcp),
            ("replica", args.replica),
            ("fair", args.sched_fair),
            ("tenant_weights", args.sched_tenant_weights),
            ("obs_stream", args.obs_stream),
            ("obs_flush_ms", args.obs_flush_ms),
            ("obs_baseline", args.obs_baseline),
            ("fleet_router", args.fleet_router),
            ("fleet_advertise", args.fleet_advertise),
            ("opt", args.opt),
            ("opt_max_iterations", args.opt_max_iterations),
            ("opt_iter_budget", args.opt_iter_budget),
            ("opt_max_weight", args.opt_max_weight),
            ("route_learn", args.route_learn),
            ("route_shadow_rate", args.route_shadow_rate),
            ("route_registry", args.route_registry),
            ("sessions", args.sessions),
            ("session_lease_s", args.session_lease_s),
            ("session_max", args.session_max),
            ("session_max_per_tenant", args.session_max_per_tenant),
        ):
            if val is not None:
                kwargs[key] = val
        # The pool is process-global (like the breaker), not a Server
        # field: install the size before the service boots.
        host_workers = kwargs.pop("host_workers", None)
        if host_workers is not None:
            from . import hostpool

            hostpool.configure_pool(host_workers)
        # Profiler arming is process-global too (ISSUE 11): installed
        # here, at the process entry point, never inside Server — an
        # embedded server must not leak arming into its process.
        prof_mode = kwargs.pop("profile", None)
        prof_sample = kwargs.pop("profile_sample", None)
        if prof_mode is not None or prof_sample is not None:
            from . import profile as profiling

            profiling.configure(mode=prof_mode, sample=prof_sample)
        # BCP impl selection is engine-global (like the pool and the
        # profiler): installed at the process entry point, before any
        # program compiles.
        bcp_impl = kwargs.pop("bcp", None)
        if bcp_impl is not None:
            from .engine import core as _engine_core

            _engine_core.set_bcp_impl(bcp_impl)
        serve(**kwargs)
    except FileNotFoundError:
        print(f"error: no such file: {args.config}", file=sys.stderr)
        return 2
    except (ValueError, OSError, problem_io.ProblemFormatError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


def _cmd_top(args) -> int:
    """Live terminal fleet dashboard over the router's /fleet/status +
    /fleet/metrics surfaces (ISSUE 16)."""
    from .obs import top

    return top.run(args.router, interval_s=args.interval,
                   once=args.once)


def main(argv: Optional[List[str]] = None) -> int:
    from .utils.platform_env import apply_platform_env

    apply_platform_env()
    parser = _build_parser()
    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 0
    if args.command == "resolve":
        return _cmd_resolve(args)
    if args.command == "bench":
        return _cmd_bench(args)
    if args.command == "serve":
        return _cmd_serve(args)
    if args.command == "fleet":
        return _cmd_fleet(args)
    if args.command == "route":
        return _cmd_route(args)
    if args.command == "publish":
        return _cmd_publish(args)
    if args.command == "optimize":
        return _cmd_optimize(args)
    if args.command == "explain":
        return _cmd_optimize(args, explain=True)
    if args.command == "stats":
        return _cmd_stats(args)
    if args.command == "trace":
        return _cmd_trace(args)
    if args.command == "profile":
        return _cmd_profile(args)
    if args.command == "routes":
        return _cmd_routes(args)
    if args.command == "top":
        return _cmd_top(args)
    if args.command == "compiles":
        return _cmd_compiles(args)
    if args.command == "lint":
        from .analysis.cli import run_lint

        return run_lint(args)
    if args.command == "doctor":
        from .utils.tpu_doctor import run_from_args

        return run_from_args(args)
    parser.error(f"unknown command {args.command!r}")
    return 2


if __name__ == "__main__":
    sys.exit(main())
