"""Command-line interface.

The reference CLI is an empty cobra root command — "deppy, the open-source
constraint solver framework" with zero subcommands
(/root/reference/cmd/root/root.go:7-14, cmd/main.go:10-16).  SURVEY.md §3.3
directs the rebuild to make it real:

  * ``deppy resolve FILE``  — read a problem (or batch) file, print each
    Solution or the NotSatisfiable conflict set;
  * ``deppy bench``         — run the headline benchmark and print its one
    JSON line;
  * ``deppy serve``         — run the batch-resolution service (the analog
    of the reference's controller manager, main.go:46-86).

Exit codes: 0 = all problems satisfiable, 1 = at least one unsatisfiable,
2 = bad input / usage, 3 = incomplete (iteration budget exhausted before a
definitive answer — the reference's ErrIncomplete, solve.go:14).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import io as problem_io
from .sat.errors import DuplicateIdentifier, InternalSolverError


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="deppy",
        description="deppy-tpu: an open-source constraint solver framework, "
        "TPU-native rebuild",
    )
    sub = parser.add_subparsers(dest="command")

    p_resolve = sub.add_parser(
        "resolve", help="resolve a problem file and print the solution(s)"
    )
    p_resolve.add_argument("file", help="JSON problem file (see deppy_tpu.io)")
    p_resolve.add_argument(
        "--backend",
        choices=["auto", "host", "tpu"],
        default="auto",
        help="solver backend (default: auto — tensor engine when a JAX "
        "device is usable, else the host engine)",
    )
    p_resolve.add_argument(
        "--output",
        choices=["text", "json"],
        default="text",
        help="output format (default: text)",
    )
    p_resolve.add_argument(
        "--max-steps",
        type=int,
        default=None,
        help="iteration budget per problem; exceeding it reports incomplete",
    )
    p_resolve.add_argument(
        "--checkpoint-dir",
        default=None,
        metavar="DIR",
        help="persist each dispatch group's results under DIR and resume "
        "a crashed batch run from its completed groups (tensor backend; "
        "see deppy_tpu.engine.checkpoint)",
    )

    p_bench = sub.add_parser(
        "bench", help="run the headline benchmark (one JSON line on stdout)"
    )
    p_bench.add_argument("--problems", type=int, default=4096)
    p_bench.add_argument("--length", type=int, default=48)

    p_serve = sub.add_parser(
        "serve", help="run the batch-resolution service"
    )
    # Serve flags default to None (sentinel) so precedence layers cleanly:
    # built-in defaults < --config file values < explicitly passed flags.
    p_serve.add_argument(
        "--bind-address", default=None,
        help="API + metrics listen address (reference main.go:48-49 "
        "metrics-bind-address; default :8080)",
    )
    p_serve.add_argument(
        "--health-probe-bind-address", default=None,
        help="healthz/readyz listen address (reference main.go:50; "
        "default :8081)",
    )
    p_serve.add_argument(
        "--backend", choices=["auto", "host", "tpu"], default=None
    )
    p_serve.add_argument("--max-steps", type=int, default=None)
    p_serve.add_argument(
        "--config", default=None, metavar="FILE",
        help="ResolverConfig file (the analog of the reference's "
        "controller_manager_config.yaml, config/manager/"
        "controller_manager_config.yaml:1-11); explicitly passed flags "
        "override file values",
    )
    p_doctor = sub.add_parser(
        "doctor",
        help="diagnose the accelerator backend (probe in a killable "
        "subprocess, classify healthy / worker-restarting / plugin "
        "failure / no accelerator; exits 0 only on healthy)",
    )
    from .utils.tpu_doctor import add_doctor_args

    add_doctor_args(p_doctor)
    return parser


# ResolverConfig file keys → serve() kwargs (config/manager/
# resolver_config.yaml).  Parsed as YAML when available, JSON otherwise
# (the shipped config is valid YAML; JSON configs work without pyyaml).
_CONFIG_KEYS = {
    "bindAddress": ("bind_address", str),
    "healthProbeBindAddress": ("probe_address", str),
    "backend": ("backend", str),
    "maxSteps": ("max_steps", int),
}


def _load_serve_config(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    try:
        import yaml

        try:
            doc = yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise problem_io.ProblemFormatError(
                f"config file {path}: invalid YAML: {e}"
            ) from e
    except ImportError:
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as e:
            raise problem_io.ProblemFormatError(
                f"config file {path}: invalid JSON: {e}"
            ) from e
    if not isinstance(doc, dict):
        raise problem_io.ProblemFormatError(
            f"config file {path}: expected a mapping, got {type(doc).__name__}"
        )
    out = {}
    for key, (kwarg, cast) in _CONFIG_KEYS.items():
        if key in doc and doc[key] is not None:
            out[kwarg] = cast(doc[key])
    return out


def _cmd_resolve(args) -> int:
    try:
        problems, is_batch = problem_io.load_document(args.file)
    except FileNotFoundError:
        print(f"error: no such file: {args.file}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot read {args.file}: {e}", file=sys.stderr)
        return 2
    except problem_io.ProblemFormatError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    from .resolution.facade import BatchResolver

    try:
        results = BatchResolver(
            backend=args.backend, max_steps=args.max_steps,
            checkpoint_dir=args.checkpoint_dir,
        ).solve(problems)
    except (DuplicateIdentifier, InternalSolverError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    rendered = [problem_io.result_to_dict(res) for res in results]
    statuses = {r["status"] for r in rendered}
    rc = 3 if "incomplete" in statuses else (1 if "unsat" in statuses else 0)

    if args.output == "json":
        # Output shape is a function of the *input* form: a batch document
        # always yields {"results": [...]}, a single problem a bare object.
        doc = {"results": rendered} if is_batch else rendered[0]
        json.dump(doc, sys.stdout, indent=2)
        print()
        return rc

    for i, r in enumerate(rendered):
        prefix = f"problem {i}: " if is_batch else ""
        if r["status"] == "sat":
            sel = ", ".join(r["selected"]) if r["selected"] else "(nothing)"
            print(f"{prefix}resolution set: {sel}")
        elif r["status"] == "unsat":
            print(f"{prefix}constraints not satisfiable: "
                  + ", ".join(r["conflicts"]))
        else:
            print(f"{prefix}resolution incomplete: {r['error']}")
    return rc


def _cmd_bench(args) -> int:
    from .benchmarks import headline

    try:
        headline.run(n_problems=args.problems, length=args.length)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


def _cmd_serve(args) -> int:
    from .service import serve

    # Precedence: built-in defaults < --config file < explicit flags
    # (the reference's flag-vs-ControllerManagerConfig behavior).  Flags
    # default to None, so a non-None parsed value IS an explicit flag.
    kwargs = {
        "bind_address": ":8080",
        "probe_address": ":8081",
        "backend": "auto",
        "max_steps": None,
    }
    try:
        if args.config:
            kwargs.update(_load_serve_config(args.config))
        for key, val in (
            ("bind_address", args.bind_address),
            ("probe_address", args.health_probe_bind_address),
            ("backend", args.backend),
            ("max_steps", args.max_steps),
        ):
            if val is not None:
                kwargs[key] = val
        serve(**kwargs)
    except FileNotFoundError:
        print(f"error: no such file: {args.config}", file=sys.stderr)
        return 2
    except (ValueError, OSError, problem_io.ProblemFormatError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    from .utils.platform_env import apply_platform_env

    apply_platform_env()
    parser = _build_parser()
    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 0
    if args.command == "resolve":
        return _cmd_resolve(args)
    if args.command == "bench":
        return _cmd_bench(args)
    if args.command == "serve":
        return _cmd_serve(args)
    if args.command == "doctor":
        from .utils.tpu_doctor import run_from_args

        return run_from_args(args)
    parser.error(f"unknown command {args.command!r}")
    return 2


if __name__ == "__main__":
    sys.exit(main())
