"""Shared size-class table: the ONE declaration of the engine's padded
shape ladder (ISSUE 12 satellite).

The driver's padding economics (:mod:`deppy_tpu.engine.driver`) and the
static block-contract checker (:mod:`deppy_tpu.analysis.block_contract`)
both reason about the same size classes — which dims a problem of a
given cost pays for, and whether adjacent classes are far enough apart
that the partitioner can ever separate them.  Before this module each
side carried its own copy (``driver.SPLIT_RATIO`` + implicit buckets on
one side, ``block_contract.SIZE_CLASSES`` on the other) and nothing but
review kept them aligned.  Now both import from here; the
``contract-drift`` lint rule anchors on THIS file.

Import-light on purpose (stdlib only, like :mod:`deppy_tpu.config`):
the analysis tier must evaluate the contracts in CI before a JAX
backend exists, so this module must never pull the engine in.

Ladder semantics: each class declares the padded dims a problem
assigned to it can pay at most — ``C`` clause rows, ``NV`` problem
vars, ``NCON`` applied constraints (``V = NV + NCON`` variables,
``Wv = ceil(V/32)`` bitplane words) — plus ``OCC``, the per-class cap
on the watched-literal clause bank's literal-occurrence width (a batch
whose max occurrence exceeds its class cap ships dummy banks and runs
the dense propagation program instead; see
:mod:`deppy_tpu.engine.clause_bank`).  Classes are ordered by
:func:`class_cost`; adjacent classes must differ by at least
:data:`SPLIT_RATIO` in padded cost or the partitioner could never
separate them (the ``padding-waste`` contract).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

WORD = 32

# Only split a batch at a size-class boundary when the padded per-lane
# cost ratio across it is at least this factor (a smaller jump pays more
# in extra dispatches than it saves in padding).
SPLIT_RATIO = 2.0

# Declared size classes: padded dims per the driver's power-of-two
# bucketing (:func:`bucket`).  The xs floor matches the 64-clause
# catalog minimum; the xl caps mirror pallas_bcp's documented VMEM
# budget (C <= 8192 rows, Wv <= 128 words = 4096 vars).  ``OCC`` tunes
# the watched-literal bank width per class: small classes keep narrow
# adjacency (a 64-clause problem's literals occur in few clauses), the
# big classes pay wider banks because that is exactly where the dense
# scan-every-clause program wastes the most.
SIZE_CLASSES: Dict[str, Dict[str, int]] = {
    "xs": {"C": 64, "NV": 128, "NCON": 64, "OCC": 32},
    "s": {"C": 256, "NV": 256, "NCON": 128, "OCC": 32},
    "m": {"C": 1024, "NV": 1024, "NCON": 512, "OCC": 64},
    "l": {"C": 4096, "NV": 2048, "NCON": 1024, "OCC": 128},
    "xl": {"C": 8192, "NV": 3072, "NCON": 1024, "OCC": 128},
}


def bucket(n: int, minimum: int = 1) -> int:
    """Round up to the next power of two (>= minimum) — the driver's
    padding quantum, shared so class arithmetic and live padding can
    never disagree."""
    n = max(n, minimum)
    out = 1
    while out < n:
        out <<= 1
    return out


def wv(cls: Dict[str, int]) -> int:
    """Bitplane words of a class's variable set."""
    return -(-(cls["NV"] + cls["NCON"]) // WORD)


def cost_proxy(n_clauses: int, n_vars: int, n_cons: int) -> int:
    """Padded per-lane cost proxy: clause-plane area dominates BCP; the
    var count drives DPLL snapshot size and iteration count.  Inputs
    are LIVE sizes; the proxy buckets them exactly like the driver
    pads."""
    NV = bucket(max(n_vars, 1))
    NCON = bucket(max(n_cons, 1))
    Wv = -(-(NV + NCON) // WORD)
    C = bucket(max(n_clauses, 1))
    return (C + 2 * NV) * Wv


def class_cost(cls: Dict[str, int]) -> int:
    """:func:`cost_proxy` over a declared class's padded dims."""
    return (cls["C"] + 2 * cls["NV"]) * wv(cls)


def ordered_classes() -> List[Tuple[str, Dict[str, int]]]:
    """Classes sorted by padded cost (the ladder order)."""
    return sorted(SIZE_CLASSES.items(), key=lambda kv: class_cost(kv[1]))


# Precomputed ladder bounds: (upper cost, name), ascending.
_LADDER: List[Tuple[int, str]] = [
    (class_cost(cls), name) for name, cls in ordered_classes()
]


def class_of_cost(cost: int) -> str:
    """The smallest declared class whose padded cost covers ``cost``
    (problems past the xl cap stay in xl — the driver's per-bucket dims
    still shrink-to-fit, the ladder only draws partition boundaries)."""
    for bound, name in _LADDER:
        if cost <= bound:
            return name
    return _LADDER[-1][1]


def occ_cap(name: str) -> int:
    """The class's watched-bank occurrence-width cap."""
    return SIZE_CLASSES[name]["OCC"]
