"""deppy_tpu — a TPU-native constraint-resolution framework.

A ground-up rebuild of the capabilities of the reference dependency
resolver (entities → constraint generators → variables → preference-ordered,
cardinality-minimized SAT resolution with human-readable unsat cores),
re-architected for TPU hardware: constraints lower to dense padded clause
tensors plus native cardinality rows, and resolution runs as a lockstep
batched DPLL inside ``jax.lax.while_loop`` — vmapped over thousands of
independent problems and sharded across a device mesh.

Layers (bottom-up, mirroring SURVEY.md §1):
  * :mod:`deppy_tpu.sat`     — constraint vocabulary, tensor lowering, host
    reference engine, solver facade (reference pkg/sat).
  * :mod:`deppy_tpu.engine`  — the batched TPU tensor engine (replaces gini).
  * :mod:`deppy_tpu.ops`     — device kernels (BCP round; Pallas variants).
  * :mod:`deppy_tpu.entity`  — entity/data layer (reference pkg/entitysource).
  * :mod:`deppy_tpu.resolution` — constraint-generation API + resolution
    facade (reference pkg/constraints + pkg/solver).
  * :mod:`deppy_tpu.parallel` — mesh/sharding utilities.
  * :mod:`deppy_tpu.models`  — benchmark problem families (BASELINE.json).
"""

__version__ = "0.1.0"

from . import entity, hostpool, models, resolution, sat, utils

__all__ = ["entity", "hostpool", "models", "resolution", "sat", "utils",
           "__version__"]
