"""Stateful-session benchmark (ISSUE 20): interactive exploration via a
retained session vs catalog-re-resolve-per-step.

Interactive traffic is conversation-shaped: an operator pins an entity,
asks for the plan, pins another, asks again — N small steps against ONE
catalog epoch.  Stateless serving answers each step with the full
``POST /v1/resolve`` cost: the client re-derives the whole catalog
document with its accumulated assumptions folded in as constraints,
ships it, and the server re-parses, re-validates, and re-encodes the
catalog before solving from cold.  A resolution session
(``POST /v1/session`` + ``/{id}/op``) retains the encoded problem and
decode vocabulary server-side, so each step ships only the delta (one
op document) and the solve warm-starts from the session's last model.

Both passes drive the SAME exploration walk over live HTTP against the
same single-replica service (host backend — the per-step win this
workload measures is retained-state vs re-shipped-state, which no
accelerator changes), and every step's answer must be byte-identical:
the session op's ``result`` object vs the one-shot oracle's
``results[0]`` for the equivalent derived document — the fuzz
differential's contract, measured instead of asserted-only.

Emits one JSON record in the bench.py contract: ``value`` the session
pass's mean milliseconds per solve-carrying step, ``vs_baseline`` the
one-shot-to-session per-step ratio (the >= 3x acceptance), plus both
passes' latency distributions and the answer-identity verdict.
"""

from __future__ import annotations

import argparse
import json
import os
import time
from http.client import HTTPConnection
from typing import List, Optional

from .harness import log


def session_catalog(bundles: int, size: int) -> dict:
    """The retained catalog: bundle 0 is mandatory behind a dependency
    chain, every other bundle an independent optional chain — the shape
    real package catalogs decompose into (bundles share no edges), so an
    assumption's consequence cone is one bundle, not the world.  Pinning
    an optional entity genuinely changes the answer (it drags its whole
    chain in); excluding one genuinely constrains it."""
    variables = []
    for b in range(bundles):
        for j in range(size):
            cons = []
            if j == 0 and b == 0:
                cons.append({"type": "mandatory"})
            if j < size - 1:
                cons.append({"type": "dependency",
                             "ids": [f"b{b}v{j + 1}"]})
            variables.append({"id": f"b{b}v{j}", "constraints": cons})
    return {"variables": variables}


def walk_steps(bundles: int, size: int, steps: int) -> List[tuple]:
    """The exploration walk: step ``i`` additionally pins one entity
    from a rotating bundle (installed for even steps, excluded for odd)
    — every step's accumulated assumption set is distinct, so the
    stateless baseline can never serve a step from the exact-result
    cache."""
    out = []
    for i in range(steps):
        b = 1 + (i % max(bundles - 1, 1))
        j = (i // max(bundles - 1, 1)) % size
        out.append((f"b{b}v{j}", i % 2 == 0))
    return out


def derived_doc(doc: dict, assumptions: List[tuple]) -> dict:
    """The stateless client's per-step document: the full catalog with
    each accumulated (id, installed) assumption folded in as a
    mandatory/prohibited constraint — what a session-less client must
    re-ship and the server must re-encode, every step."""
    extra: dict = {}
    for ident, installed in assumptions:
        extra.setdefault(ident, []).append(
            {"type": "mandatory" if installed else "prohibited"})
    variables = []
    for v in doc["variables"]:
        cons = list(v.get("constraints") or [])
        cons += extra.get(v["id"], [])
        variables.append({"id": v["id"], "constraints": cons})
    return {"variables": variables}


def _request(port: int, method: str, path: str, body=None, headers=None):
    conn = HTTPConnection("127.0.0.1", port, timeout=120)
    h = dict(headers or {})
    payload = None
    if body is not None:
        payload = json.dumps(body)
        h.setdefault("Content-Type", "application/json")
    conn.request(method, path, body=payload, headers=h)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def _percentile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(int(q * len(ordered)), len(ordered) - 1)
    return ordered[idx]


def _dist(samples: List[float]) -> dict:
    return {
        "steps": len(samples),
        "mean_ms": round(sum(samples) / max(len(samples), 1) * 1e3, 3),
        "p50_ms": round(_percentile(samples, 0.50) * 1e3, 3),
        "p99_ms": round(_percentile(samples, 0.99) * 1e3, 3),
        "wall_s": round(sum(samples), 3),
    }


def session_pass(port: int, doc: dict, steps: List[tuple]) -> tuple:
    """The retained-session walk: create once, then one assume op + one
    resolve op per step.  The per-step sample is the CLIENT-visible
    wall of the whole step (both ops) — the number an interactive
    operator feels."""
    status, body = _request(port, "POST", "/v1/session", doc)
    if status != 200:
        raise RuntimeError(f"session create: HTTP {status} {body[:200]!r}")
    sid = json.loads(body)["session"]["id"]
    op_path = f"/v1/session/{sid}/op"
    samples: List[float] = []
    answers: List[str] = []
    for ident, installed in steps:
        t0 = time.perf_counter()
        status, body = _request(
            port, "POST", op_path,
            {"op": "assume", "identifiers": [ident],
             "installed": installed})
        if status != 200:
            raise RuntimeError(f"assume {ident}: HTTP {status}")
        status, body = _request(port, "POST", op_path, {"op": "resolve"})
        samples.append(time.perf_counter() - t0)
        if status != 200:
            raise RuntimeError(f"resolve: HTTP {status} {body[:200]!r}")
        answers.append(json.dumps(json.loads(body)["result"],
                                  sort_keys=True))
    return samples, answers


def oneshot_pass(port: int, doc: dict, steps: List[tuple]) -> tuple:
    """The stateless walk: per step, fold the accumulated assumptions
    into the full catalog document client-side and POST /v1/resolve.
    The sample includes the client's document derivation — that cost IS
    part of being session-less, exactly as re-parse and re-encode are
    part of the server's."""
    samples: List[float] = []
    answers: List[str] = []
    assumptions: List[tuple] = []
    for step in steps:
        t0 = time.perf_counter()
        assumptions.append(step)
        status, body = _request(port, "POST", "/v1/resolve",
                                derived_doc(doc, assumptions))
        samples.append(time.perf_counter() - t0)
        if status != 200:
            raise RuntimeError(f"oracle resolve: HTTP {status} "
                               f"{body[:200]!r}")
        answers.append(json.dumps(json.loads(body)["results"][0],
                                  sort_keys=True))
    return samples, answers


def run(bundles: int = 96, size: int = 8, steps: int = 48,
        out_path: Optional[str] = None) -> dict:
    from ..service import Server

    log(f"session workload: {bundles} bundles x {size} = "
        f"{bundles * size} variables, {steps} exploration steps")
    doc = session_catalog(bundles, size)
    walk = walk_steps(bundles, size, steps)
    srv = Server(bind_address="127.0.0.1:0", probe_address="127.0.0.1:0",
                 backend="host", sched="on")
    srv.start()
    try:
        sess_samples, sess_answers = session_pass(srv.api_port, doc, walk)
        one_samples, one_answers = oneshot_pass(srv.api_port, doc, walk)
    finally:
        srv.shutdown()
    sess = _dist(sess_samples)
    oneshot = _dist(one_samples)
    identical = sess_answers == one_answers
    ratio = (oneshot["mean_ms"] / sess["mean_ms"]
             if sess["mean_ms"] else 0.0)
    record = {
        "metric": ("interactive exploration ms/step "
                   "(retained session vs catalog-re-resolve-per-step)"),
        "value": sess["mean_ms"],
        "unit": "ms",
        "vs_baseline": round(ratio, 2),
        "workload": "session",
        "bundles": bundles,
        "bundle_size": size,
        "n_vars": bundles * size,
        "n_steps": steps,
        "answers_identical": identical,
        "session": sess,
        "oneshot": oneshot,
        "backend": "host",
    }
    if not identical:
        record["error"] = ("session answers diverged from the one-shot "
                           "oracle — the differential contract is broken")
        record["value"] = 0.0
        record["vs_baseline"] = 0.0
    if out_path:
        import platform

        full = {
            "issue": 20,
            "record": "session_r20",
            "platform": {
                "python": platform.python_version(),
                "machine": platform.machine(),
                "cpus": os.cpu_count(),
                "jax_platforms": (os.environ.get("JAX_PLATFORMS")
                                  or "(default)"),
            },
            "note": ("one live host-backend service; both passes drive "
                     "the identical exploration walk over HTTP; the "
                     "session pass pays create once then per-step op "
                     "deltas (retained encoded catalog, warm-started "
                     "solves), the one-shot pass re-derives, re-ships, "
                     "and cold-resolves the full catalog document every "
                     "step; every step's answer must match byte for "
                     "byte"),
            **record,
        }
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(full, fh, indent=1)
            fh.write("\n")
        log(f"wrote {out_path}")
    return record


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--bundles", type=int, default=96)
    ap.add_argument("--size", type=int, default=8)
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--out", default=None,
                    help="also write the full record (the benchmarks/"
                    "results/session_r20.json artifact)")
    args = ap.parse_args()
    record = run(bundles=args.bundles, size=args.size, steps=args.steps,
                 out_path=args.out)
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
