"""Full benchmark suite: the BASELINE.json workload configs + extras.

The reference publishes no numbers (SURVEY.md §6), so this suite produces
the rebuild's own: for each config, a sampled serial host-engine baseline
(the stand-in for the reference's single-threaded gini solver) and the
batched device rate.  Results feed BASELINE.md.

Run: ``python -m deppy_tpu.benchmarks.suite [--quick] [--out FILE]``.
Prints one JSON object per config on stdout (one line each), detail on
stderr.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict, List, Optional

from ..models import (
    giant_pinned_conflict,
    gvk_conflict_catalog,
    operatorhub_catalog,
    pinned_tenant_catalog,
    random_instance,
    version_pinned_chains,
)
from .harness import log


def _configs(quick: bool) -> List[Dict]:
    """The five BASELINE.json configs plus two extras (the UNSAT-heavy
    fleet and the giant-UNSAT core-extraction case).  ``quick`` shrinks
    batch sizes for CI smoke runs; full sizes match the config
    descriptions."""
    scale = 8 if quick else 1
    return [
        {
            "name": "single operatorhub catalog resolve (~200 bundles)",
            "gen": lambda s: operatorhub_catalog(
                n_packages=40, versions_per_package=5, seed=s
            ),
            "n": 1,
        },
        {
            "name": "batched 1k independent resolves (random catalog subsets)",
            "gen": lambda s: random_instance(length=48, seed=s),
            "n": 1024 // scale,
        },
        {
            "name": "version-pin + deep transitive chains (AtMost-1 per package)",
            "gen": lambda s: version_pinned_chains(depth=20, width=3, seed=s),
            "n": 256 // scale,
        },
        {
            "name": "GVK-uniqueness Conflict-heavy",
            "gen": lambda s: gvk_conflict_catalog(
                n_groups=20, providers_per_group=4, n_required=10, seed=s
            ),
            "n": 256 // scale,
        },
        {
            "name": "fleet-scale: 10k cluster-states x shared catalog (mesh)",
            "gen": lambda s: gvk_conflict_catalog(
                n_groups=12, providers_per_group=3, n_required=6, seed=s
            ),
            "n": 10_000 // scale,
            "mesh": True,
        },
        # Beyond BASELINE.json's five: the UNSAT-heavy fleet shape, where
        # the unsat-core extraction phase (gated or compacted deletion,
        # chunk-first probing) dominates rather than idles.
        {
            "name": "UNSAT-heavy fleet: pinned tenants over shared GVK catalog",
            "gen": lambda s: pinned_tenant_catalog(seed=s),
            "n": 2048 // scale,
            "mesh": True,
        },
        # ONE giant unsatisfiable catalog: a 3-constraint core buried in
        # ~1.7k constraints — exercises host-routed core extraction
        # (driver.HOST_CORE_NCONS).  Quick mode stays above the routing
        # threshold with a lighter catalog.
        {
            "name": "giant catalog UNSAT: pinned conflict, core extraction",
            "gen": (lambda s: giant_pinned_conflict(
                n_packages=150, versions_per_package=6, seed=s
            )) if quick else (lambda s: giant_pinned_conflict(seed=s)),
            "n": 1,
        },
    ]


def _bench_config(cfg: Dict, host_sample: int = 16) -> Dict:
    from ..sat.encode import encode
    from .harness import bench_problems

    n = cfg["n"]
    log(f"--- {cfg['name']} (n={n})")
    t0 = time.perf_counter()
    problems = [encode(cfg["gen"](s)) for s in range(n)]
    encode_s = time.perf_counter() - t0
    log(f"encode: {n} problems in {encode_s:.2f}s")

    mesh = None
    if cfg.get("mesh"):
        import jax

        from ..parallel import default_mesh

        if len(jax.devices()) > 1:
            mesh = default_mesh(jax.devices())
            log(f"mesh: {len(jax.devices())} devices")

    m = bench_problems(problems, host_sample=host_sample, mesh=mesh)
    host_s = m["host_s_per_problem"]
    return {
        "config": cfg["name"],
        "n_problems": n,
        "host_ms_per_problem": round(host_s * 1e3, 3),
        "host_rate": round(1.0 / host_s, 2),
        "device_seconds": round(m["device_seconds"], 4),
        "device_rate": round(m["device_rate"], 2),
        "speedup_vs_serial_host": round(m["device_rate"] * host_s, 3),
        # Startup attribution (ISSUE 4 satellite): every record carries
        # the backend first-touch wall and this config's compile
        # warm-up, so probe/retry stalls are visible in the JSON.
        "probe_wall_s": round(m["probe_wall_s"], 3),
        "warmup_seconds": round(m["warmup_seconds"], 3),
        # Compile-guard ledger delta (ISSUE 8): jit-entry traces paid
        # by this config's warm-up + timed dispatches.
        "n_compiles": m["n_compiles"],
        # Engine-economics columns (ISSUE 11) from the trip ledger.
        "useful_work_ratio": m["useful_work_ratio"],
        "straggler_p99_ratio": m["straggler_p99_ratio"],
        "pad_waste_ratio": m["pad_waste_ratio"],
        "sat": m["sat"],
        "unsat": m["unsat"],
    }


def _bench_encode_only(n: int = 200) -> Dict:
    """The reference's ``BenchmarkNewInput`` analog (bench_test.go:79-86):
    encode-only (constraint lowering, no solve) on the same seeded
    256-variable random instance the solve benchmark uses."""
    from ..sat.encode import encode

    vs = random_instance()  # length=256, seed=9 — the bench_test instance
    encode(vs)  # warm allocator/caches
    t0 = time.perf_counter()
    for _ in range(n):
        encode(vs)
    per = (time.perf_counter() - t0) / n
    log(f"encode-only: {per * 1e6:.0f} us/encode")
    return {
        "config": "encode-only (BenchmarkNewInput analog, 256-var seeded instance)",
        "encode_us": round(per * 1e6, 1),
        "encodes_per_sec": round(1.0 / per, 1),
    }


def run(quick: bool = False, out_path: Optional[str] = None,
        only: Optional[int] = None) -> List[Dict]:
    import jax

    from .harness import probe_wall_s

    probe_wall_s()  # time the first backend touch before anything else
    log(f"jax backend: {jax.default_backend()} devices={jax.devices()}")
    results = []
    for i, cfg in enumerate(_configs(quick)):
        if only is not None and i != only:
            continue
        res = _bench_config(cfg)
        print(json.dumps(res), flush=True)
        results.append(res)
    if only is None:
        res = _bench_encode_only()
        print(json.dumps(res), flush=True)
        results.append(res)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(results, f, indent=2)
        log(f"wrote {out_path}")
    return results


def main() -> None:
    import os
    import signal

    from ..utils.platform_env import apply_platform_env

    # Same orphan guard as headline.main: a caller that dies mid-suite
    # must not leave this process wedged on the accelerator worker.
    sd = os.environ.get("DEPPY_BENCH_SELF_DESTRUCT")
    if sd and sd.isdigit() and int(sd) > 0:
        signal.alarm(int(sd))

    apply_platform_env()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="shrink batch sizes ~8x for smoke runs")
    ap.add_argument("--out", default=None, help="also write a JSON file")
    ap.add_argument("--only", type=int, default=None,
                    help="run a single config by index (0-6)")
    args = ap.parse_args()
    run(quick=args.quick, out_path=args.out, only=args.only)


if __name__ == "__main__":
    main()
