"""Shared measurement harness for all benchmarks.

One methodology, used by both the headline benchmark and the full suite:
sampled serial host-engine baseline (the stand-in for the reference's
single-threaded gini solver), an untimed compile warm-up, then one timed
batched device dispatch.
"""

from __future__ import annotations

import sys
import time
from typing import Dict, Sequence


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


_PROBE_WALL_S = None


def probe_wall_s() -> float:
    """Wall-clock seconds for the first touch of the JAX backend (PJRT
    init + device enumeration), measured once per process and cached.

    BENCH_r01–r05 carried multi-minute backend-probe/init stalls that
    were invisible in the emitted JSON (the retries happened before any
    timed section); recording the first-touch wall in every BENCH record
    makes them attributable without a rerun.  Call this BEFORE anything
    else touches the backend (``jax.default_backend()``,
    ``jax.devices()``) or the measurement reads ~0."""
    global _PROBE_WALL_S
    if _PROBE_WALL_S is None:
        import jax

        t0 = time.perf_counter()
        jax.devices()
        _PROBE_WALL_S = time.perf_counter() - t0
        if _PROBE_WALL_S > 1.0:
            log(f"backend probe: {_PROBE_WALL_S:.1f}s to first device")
    return _PROBE_WALL_S


def bench_problems(problems: Sequence, host_sample: int = 16,
                   mesh=None, serving_mesh=None) -> Dict:
    """Measure a list of lowered problems: host ms/problem (serial,
    sampled), device rate (batched, post-warm-up).  Returns the raw
    numbers; callers shape them into their own output records.

    ``serving_mesh`` routes the timed dispatch through the ISSUE 6
    batch-axis sharded entry (``driver.solve_problems_sharded``) so the
    mesh scaling curve is measured with the exact code path the
    scheduler serves with; ``mesh`` stays the clause-axis mesh of the
    historical dispatch paths."""
    from ..engine import core, driver
    from ..sat.errors import NotSatisfiable
    from ..sat.host import HostEngine

    if not problems:
        raise ValueError("problems must be non-empty")
    if host_sample <= 0:
        raise ValueError("host_sample must be positive")
    n = len(problems)
    n_devices = int(getattr(serving_mesh, "size", 1) or 1)

    def dispatch():
        if serving_mesh is not None:
            return driver.solve_problems_sharded(problems,
                                                 mesh=serving_mesh)
        return driver.solve_problems(problems, mesh=mesh)
    # First backend touch is timed HERE, before the warm-up pays it
    # invisibly — direct bench_problems callers get the real init stall
    # in their record, not ~0 measured after the fact.
    probe_s = probe_wall_s()

    from ..analysis import compileguard

    sample = problems[: min(host_sample, n)]
    t_start = time.perf_counter()
    pass_times = []
    while True:
        t0 = time.perf_counter()
        for p in sample:
            try:
                HostEngine(p).solve()
            except NotSatisfiable:
                pass  # UNSAT is a valid (timed) outcome; errors propagate
        pass_times.append((time.perf_counter() - t0) / len(sample))
        # Tiny samples (n=1 configs) repeat until the measurement window
        # is long enough to dominate timer/GC jitter.  Best-of-passes, the
        # same statistic the device side uses below — keeping the
        # host/device ratio an apples-to-apples min/min.
        if (time.perf_counter() - t_start >= 0.25
                or len(sample) >= host_sample):
            break
    host_s = min(pass_times)
    log(f"host: {host_s * 1e3:.2f} ms/problem ({1.0 / host_s:.1f}/s serial)")

    compiles_before = compileguard.trace_count()
    t0 = time.perf_counter()
    dispatch()  # includes compile
    warm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    results = dispatch()
    dev_s = time.perf_counter() - t0
    # Sub-50ms dispatches (the single-problem config) are dominated by
    # timer/GC jitter in one sample: re-time and keep the best.
    if dev_s < 0.05:
        reps = max(3, int(0.2 / max(dev_s, 1e-4)))
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            results = dispatch()
            times.append(time.perf_counter() - t0)
        dev_s = min(times + [dev_s])
    n_sat = sum(1 for r in results if r.outcome == core.SAT)
    n_unsat = sum(1 for r in results if r.outcome == core.UNSAT)
    rate = n / dev_s
    log(
        f"device: {n} in {dev_s:.3f}s = {rate:.1f}/s "
        f"({n_sat} sat / {n_unsat} unsat; warm-up {warm_s:.1f}s)"
    )
    from .. import hostpool

    out = {
        "n_problems": n,
        "host_s_per_problem": host_s,
        "device_seconds": dev_s,
        "device_rate": rate,
        "warmup_seconds": warm_s,
        # Backend first-touch wall (ISSUE 4 satellite): whoever touched
        # the backend first — this harness or an earlier probe_wall_s()
        # caller — the measured init cost rides every record.
        "probe_wall_s": probe_s,
        # Host-path concurrency (ISSUE 5 satellite): the worker-pool
        # size the breaker-open / host-backend path would use under this
        # record's configuration (0 = inline serial engine).  The serial
        # host_s_per_problem sample above is deliberately per-CORE — the
        # pool speedup itself is tracked by
        # benchmarks/results/hostpool_baseline.json (host_baseline
        # --pool), not folded into the device-vs-host ratio.
        "host_workers": hostpool.effective_workers(),
        # Mesh-serving columns (ISSUE 6): how many devices the timed
        # dispatch sharded over (1 = historical single-device path) and
        # the per-device throughput — the scaling-curve numerator every
        # MULTICHIP/BENCH round tracks.
        "n_devices": n_devices,
        "per_device_rate": rate / n_devices,
        # Compile-guard ledger delta across warm-up + timed dispatches
        # (ISSUE 8): how many jit-entry traces the measured section
        # paid.  The warm-up should absorb them all — a nonzero count
        # beyond it in later rounds is the compile-storm tell the
        # runtime guard asserts on under DEPPY_TPU_COMPILE_GUARD=1.
        "n_compiles": compileguard.trace_count() - compiles_before,
        "sat": n_sat,
        "unsat": n_unsat,
    }
    # Occupancy/fallback telemetry from the timed dispatch (ISSUE 1): the
    # driver publishes a SolveReport per solve_problems call; carrying it
    # in the record means every BENCH_*.json row shows how much of the
    # measured batch was padding and which escalation stage resolved it.
    from .. import telemetry

    rep = telemetry.last_report()
    if rep is not None:
        out["telemetry"] = {
            "batch_fill_ratio": round(rep.batch_fill_ratio, 4),
            "pad_waste_ratio": round(rep.pad_waste_ratio, 4),
            "escalation_stage": rep.escalation_stage,
            "host_fallback_rows": rep.host_fallback_rows,
            "backtracks": rep.backtracks,
            "steps": rep.steps,
            "n_chunks": rep.n_chunks,
            "n_buckets": rep.n_buckets,
        }
        log(rep.format_table())
    # Engine-economics columns (ISSUE 11): one extra, UNTIMED dispatch
    # with the trip ledger armed at full sampling sources the
    # useful-work / straggler / pad-waste ratios from the profiler's
    # own machinery without perturbing the timed rate above — BENCH_r*
    # trajectories then pin engine economics, not just throughput.
    from .. import profile

    with profile.override("on", 1.0):
        dispatch()
    lrep = telemetry.last_report()
    if lrep is not None and lrep.profiled_dispatches:
        out["useful_work_ratio"] = round(lrep.useful_work_ratio, 4)
        out["straggler_p99_ratio"] = round(lrep.straggler_p99_ratio, 4)
        out["pad_waste_ratio"] = round(lrep.pad_waste_ratio, 4)
        log(f"trip ledger: useful {out['useful_work_ratio']:.3f}  "
            f"straggler-p99 {out['straggler_p99_ratio']:.3f}  "
            f"pad-waste {out['pad_waste_ratio']:.3f}")
    else:
        # The ledger dispatch routed somewhere unprofiled (pure host
        # path): the columns still exist so record schemas stay fixed.
        out["useful_work_ratio"] = 0.0
        out["straggler_p99_ratio"] = 0.0
        out["pad_waste_ratio"] = round(
            rep.pad_waste_ratio, 4) if rep is not None else 0.0
    return out
