"""Headline benchmark: batched catalog resolutions/sec, device vs host.

Workload: BASELINE.json config 2 — a batch of independent catalog
resolutions (random catalog subsets in the reference benchmark's instance
distribution, /root/reference/pkg/sat/bench_test.go:10-64) dispatched to
the tensor engine in one vmapped solve.  Measurement methodology lives in
:mod:`deppy_tpu.benchmarks.harness` (shared with the full suite).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
plus human-readable detail on stderr.  Invoked by the repo-root
``bench.py`` (the driver's entry point) and ``deppy bench``.
"""

from __future__ import annotations

import json

from .harness import bench_problems, log


def run(n_problems: int = 512, length: int = 48, host_sample: int = 24) -> dict:
    import jax

    from ..models import random_instance
    from ..sat.encode import encode

    if n_problems <= 0:
        raise ValueError("n_problems must be positive")

    log(f"jax backend: {jax.default_backend()} devices={jax.devices()}")
    problems = [
        encode(random_instance(length=length, seed=s)) for s in range(n_problems)
    ]
    m = bench_problems(problems, host_sample=host_sample)

    result = {
        "metric": "catalog resolutions/sec (batched device vs serial host)",
        "value": round(m["device_rate"], 2),
        "unit": "problems/s",
        "vs_baseline": round(m["device_rate"] * m["host_s_per_problem"], 3),
    }
    print(json.dumps(result))
    return result
