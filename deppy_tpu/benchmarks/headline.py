"""Headline benchmark: batched catalog resolutions/sec, device vs host.

Workload: BASELINE.json config 2 — a batch of independent catalog
resolutions (random catalog subsets in the reference benchmark's instance
distribution, /root/reference/pkg/sat/bench_test.go:10-64) dispatched to
the tensor engine in one vmapped solve.  Measurement methodology lives in
:mod:`deppy_tpu.benchmarks.harness` (shared with the full suite).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
plus human-readable detail on stderr.  Invoked by the repo-root
``bench.py`` (the driver's entry point) and ``deppy bench``.
"""

from __future__ import annotations

import json

from .harness import bench_problems, log, probe_wall_s


def run(n_problems: int = 4096, length: int = 48, host_sample: int = 24,
        platform: str | None = None,
        mesh_devices: int | None = None) -> dict:
    import jax

    from ..models import random_instance
    from ..sat.encode import encode

    if n_problems <= 0:
        raise ValueError("n_problems must be positive")

    if platform:
        jax.config.update("jax_platforms", platform)
    probe_s = probe_wall_s()  # time the first backend touch explicitly
    backend = jax.default_backend()
    log(f"jax backend: {backend} devices={jax.devices()}")
    # Mesh serving (ISSUE 6): --mesh-devices / DEPPY_TPU_MESH_DEVICES
    # shards the timed dispatch over a device mesh — the same entry
    # point the scheduler drains through, so the headline number and
    # the serving path stay one code path.
    from ..parallel.mesh import serving_mesh

    smesh = serving_mesh(mesh_devices)
    if smesh is not None:
        log(f"serving mesh: {int(smesh.size)} devices (batch-axis shard)")
    problems = [
        encode(random_instance(length=length, seed=s)) for s in range(n_problems)
    ]
    m = bench_problems(problems, host_sample=host_sample,
                       serving_mesh=smesh)

    # The ratio's denominator: the committed machine-keyed median record
    # when one matches (so vs_baseline moves only when the device rate
    # does — round-4 verdict weak #3), else this run's live sample.  The
    # live rate is always reported alongside for drift visibility.
    from .host_baseline import load_pinned

    pinned = load_pinned(length)
    host_s = pinned["host_s_per_problem"] if pinned else m["host_s_per_problem"]
    if pinned:
        log(f"host denominator: pinned {1.0 / host_s:.1f}/s "
            f"(live sample {1.0 / m['host_s_per_problem']:.1f}/s)")
    else:
        log("host denominator: live sample (no matching committed "
            "host_baseline.json record)")

    result = {
        "metric": "catalog resolutions/sec (batched device vs serial host)",
        "value": round(m["device_rate"], 2),
        "unit": "problems/s",
        "vs_baseline": round(m["device_rate"] * host_s, 3),
        "backend": backend,
        "baseline_source": "pinned" if pinned else "live",
        "host_rate_live": round(1.0 / m["host_s_per_problem"], 1),
        "host_rate_used": round(1.0 / host_s, 1),
        # Startup attribution (ISSUE 4 satellite): backend first-touch
        # wall and the untimed compile warm-up — the BENCH_r01-r05
        # multi-minute probe/retry stalls were invisible without these.
        "probe_wall_s": round(probe_s, 3),
        "warmup_seconds": round(m["warmup_seconds"], 3),
        # Host-path pool size (ISSUE 5 satellite; 0 = inline serial).
        "host_workers": m["host_workers"],
        # Mesh-serving scaling columns (ISSUE 6): device count the timed
        # dispatch sharded over + throughput per device.
        "n_devices": m["n_devices"],
        "per_device_rate": round(m["per_device_rate"], 2),
        # Compile-guard ledger delta over warm-up + timed dispatches
        # (ISSUE 8): how many jit-entry traces the record paid.
        "n_compiles": m["n_compiles"],
        # Engine-economics columns (ISSUE 11), sourced from the trip
        # ledger's untimed profiled dispatch: BENCH trajectories pin
        # what the lockstep trips bought, not just throughput.
        "useful_work_ratio": m["useful_work_ratio"],
        "straggler_p99_ratio": m["straggler_p99_ratio"],
        "pad_waste_ratio": m["pad_waste_ratio"],
    }
    if "telemetry" in m:
        # Occupancy and fallback columns ride in every BENCH row (ISSUE
        # 1): a throughput regression can then be attributed to padding
        # waste or host routing without a rerun.
        result["telemetry"] = m["telemetry"]
    print(json.dumps(result), flush=True)
    return result


def main() -> None:
    import argparse
    import os
    import signal

    from ..utils.platform_env import apply_platform_env

    # Armed by bench.py: self-destruct shortly after the caller's
    # watchdog, so an orphaned run (caller killed) cannot sit wedged on
    # the accelerator worker for hours.  SIGALRM's default disposition
    # kills the process even while blocked inside PJRT C code.
    sd = os.environ.get("DEPPY_BENCH_SELF_DESTRUCT")
    if sd and sd.isdigit() and int(sd) > 0:
        signal.alarm(int(sd))

    apply_platform_env()

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) before running")
    ap.add_argument("--n-problems", type=int, default=4096)
    ap.add_argument("--length", type=int, default=48)
    ap.add_argument("--host-sample", type=int, default=24)
    ap.add_argument("--mesh-devices", type=int, default=None,
                    help="shard the timed dispatch over N devices "
                    "(-1 = all; default: DEPPY_TPU_MESH_DEVICES or off)")
    a = ap.parse_args()
    run(n_problems=a.n_problems, length=a.length, host_sample=a.host_sample,
        platform=a.platform, mesh_devices=a.mesh_devices)


if __name__ == "__main__":
    main()
