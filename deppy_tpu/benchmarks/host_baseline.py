"""Pinned serial-host denominator for the headline benchmark ratio.

Round-over-round, ``bench.py``'s ``vs_baseline`` moved 2x on denominator
noise alone: the live host sample measured 533/s in round 3 and 278/s in
round 4 on the same machine and the same engine (BENCH_r03/r04), because
a ~0.25s sampling window on a busy single-core box measures the ambient
load as much as the solver.  The reference has no such wobble — its
baseline IS the serial engine (gini, go.mod:6), pinned by version.

This module pins the denominator the same way: a committed record
(``benchmarks/results/host_baseline.json``) holding a best-of-passes
measurement of the serial host engine on the headline instance
distribution, keyed to the machine (cpu model + core count) and workload
(instance length).  The statistic is ``min`` over many passes — the SAME
statistic the live sample uses (harness.bench_problems keeps min/min so
the host/device ratio is apples-to-apples) — just taken over a window
long enough to contain a quiet moment.  ``bench.py``'s ratio uses the
pinned record whenever it matches; the live sample is still measured and
reported alongside so drift is visible (an engine change that speeds the
host solver up shows as live pulling away from pinned — refresh the
record with ``python -m deppy_tpu.benchmarks.host_baseline`` and commit
it).
"""

from __future__ import annotations

import json
import os
import statistics
import time

RESULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "..", "benchmarks", "results")
BASELINE_PATH = os.path.abspath(
    os.path.join(RESULTS_DIR, "host_baseline.json"))


def machine_key() -> str:
    """CPU model + logical core count: the denominator is machine-bound,
    and a record measured elsewhere must not pin another machine's
    ratio."""
    model = "unknown-cpu"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return f"{model} x{os.cpu_count()}"


def workload_key(length: int) -> str:
    """The host sample depends only on the instance distribution (the
    reference generator's parameters at a given length — seeds are
    fixed in :func:`measure`)."""
    return f"config2-length{length}"


def measure(length: int = 48, sample_n: int = 24, passes: int = 30) -> dict:
    """Best-of-passes serial host measurement: min over ``passes`` passes
    (matching the live sample's statistic), with the window sized to
    contain a quiet moment on a loaded box.  The median/max land in the
    record's ``spread`` for load visibility."""
    from ..models import random_instance
    from ..sat.encode import encode
    from ..sat.errors import NotSatisfiable
    from ..sat.host import HostEngine

    sample = [encode(random_instance(length=length, seed=s))
              for s in range(sample_n)]
    pass_times = []
    for _ in range(passes):
        t0 = time.perf_counter()
        for p in sample:
            try:
                HostEngine(p).solve()
            except NotSatisfiable:
                pass
        pass_times.append((time.perf_counter() - t0) / sample_n)
    host_s = min(pass_times)
    return {
        "machine": machine_key(),
        "workload": workload_key(length),
        "host_s_per_problem": host_s,
        "host_rate": 1.0 / host_s,
        "sample_n": sample_n,
        "passes": passes,
        "statistic": "min-of-passes (same as the live sample)",
        "spread": {
            "median_s": statistics.median(pass_times),
            "max_s": max(pass_times),
        },
        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
    }


def load_pinned(length: int) -> dict | None:
    """The committed record, iff it matches this machine and workload."""
    try:
        with open(BASELINE_PATH) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(rec, dict):
        return None
    if rec.get("machine") != machine_key():
        return None
    if rec.get("workload") != workload_key(length):
        return None
    s = rec.get("host_s_per_problem")
    if not isinstance(s, (int, float)) or s <= 0:
        return None
    return rec


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--length", type=int, default=48)
    ap.add_argument("--sample-n", type=int, default=24)
    ap.add_argument("--passes", type=int, default=30)
    ap.add_argument("--out", default=BASELINE_PATH)
    a = ap.parse_args()
    rec = measure(length=a.length, sample_n=a.sample_n, passes=a.passes)
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
