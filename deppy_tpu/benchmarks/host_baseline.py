"""Pinned serial-host denominator for the headline benchmark ratio.

Round-over-round, ``bench.py``'s ``vs_baseline`` moved 2x on denominator
noise alone: the live host sample measured 533/s in round 3 and 278/s in
round 4 on the same machine and the same engine (BENCH_r03/r04), because
a ~0.25s sampling window on a busy single-core box measures the ambient
load as much as the solver.  The reference has no such wobble — its
baseline IS the serial engine (gini, go.mod:6), pinned by version.

This module pins the denominator the same way: a committed record
(``benchmarks/results/host_baseline.json``) holding a best-of-passes
measurement of the serial host engine on the headline instance
distribution, keyed to the machine (cpu model + core count) and workload
(instance length).  The statistic is ``min`` over many passes — the SAME
statistic the live sample uses (harness.bench_problems keeps min/min so
the host/device ratio is apples-to-apples) — just taken over a window
long enough to contain a quiet moment.  ``bench.py``'s ratio uses the
pinned record whenever it matches; the live sample is still measured and
reported alongside so drift is visible (an engine change that speeds the
host solver up shows as live pulling away from pinned — refresh the
record with ``python -m deppy_tpu.benchmarks.host_baseline`` and commit
it).
"""

from __future__ import annotations

import json
import os
import statistics
import time

RESULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "..", "benchmarks", "results")
BASELINE_PATH = os.path.abspath(
    os.path.join(RESULTS_DIR, "host_baseline.json"))
HOSTPOOL_PATH = os.path.abspath(
    os.path.join(RESULTS_DIR, "hostpool_baseline.json"))


def machine_key() -> str:
    """CPU model + logical core count: the denominator is machine-bound,
    and a record measured elsewhere must not pin another machine's
    ratio."""
    model = "unknown-cpu"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return f"{model} x{os.cpu_count()}"


def workload_key(length: int) -> str:
    """The host sample depends only on the instance distribution (the
    reference generator's parameters at a given length — seeds are
    fixed in :func:`measure`)."""
    return f"config2-length{length}"


def measure(length: int = 48, sample_n: int = 24, passes: int = 30) -> dict:
    """Best-of-passes serial host measurement: min over ``passes`` passes
    (matching the live sample's statistic), with the window sized to
    contain a quiet moment on a loaded box.  The median/max land in the
    record's ``spread`` for load visibility."""
    from ..models import random_instance
    from ..sat.encode import encode
    from ..sat.errors import NotSatisfiable
    from ..sat.host import HostEngine

    sample = [encode(random_instance(length=length, seed=s))
              for s in range(sample_n)]
    pass_times = []
    for _ in range(passes):
        t0 = time.perf_counter()
        for p in sample:
            try:
                HostEngine(p).solve()
            except NotSatisfiable:
                pass
        pass_times.append((time.perf_counter() - t0) / sample_n)
    host_s = min(pass_times)
    return {
        "machine": machine_key(),
        "workload": workload_key(length),
        "host_s_per_problem": host_s,
        "host_rate": 1.0 / host_s,
        "sample_n": sample_n,
        "passes": passes,
        "statistic": "min-of-passes (same as the live sample)",
        "spread": {
            "median_s": statistics.median(pass_times),
            "max_s": max(pass_times),
        },
        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
    }


def measure_pool(length: int = 48, batch_n: int = 256,
                 passes: int = 5, workers: "int | None" = None) -> dict:
    """The hostpool speedup row (ISSUE 5 satellite): the same batch
    solved serially inline, through a 1-worker pool (isolating the IPC
    overhead), and through the N-worker pool — best-of-passes each, so
    the committed record tracks the pool's measured value like every
    other measured default.  ``workers`` defaults to the pool's own
    policy (min(cpu_count, 8))."""
    import time as _time

    from .. import hostpool
    from ..models import random_instance
    from ..sat.encode import encode

    if workers is None:
        workers = min(os.cpu_count() or 1, 8)
    batch = [encode(random_instance(length=length, seed=s))
             for s in range(batch_n)]

    def best(fn) -> float:
        times = []
        for _ in range(passes):
            t0 = _time.perf_counter()
            fn()
            times.append(_time.perf_counter() - t0)
        return min(times)

    inline_s = best(lambda: hostpool.solve_inline(batch))
    rows = {}
    for n in sorted({1, workers}):
        pool = hostpool.HostPool(workers=n)
        try:
            pool.solve(batch[: 2 * n])  # spawn + warm outside the clock
            rows[str(n)] = best(lambda: pool.solve(batch))
        except hostpool.HostPoolError as e:
            rows[str(n)] = None
            print(f"[host_baseline] pool({n}) unavailable: {e}",
                  file=__import__("sys").stderr)
        finally:
            pool.shutdown()
    pooled_s = rows.get(str(workers))
    return {
        "machine": machine_key(),
        "cpu_count": os.cpu_count(),
        "workload": f"{workload_key(length)}-batch{batch_n}",
        "batch_n": batch_n,
        "passes": passes,
        "statistic": "min-of-passes (same as host_baseline.json)",
        "inline_rate": batch_n / inline_s,
        "pool_rates": {n: (batch_n / s if s else None)
                       for n, s in rows.items()},
        "workers": workers,
        "speedup_vs_inline": (inline_s / pooled_s if pooled_s else None),
        # Scaling context the ratio is meaningless without: the pool
        # parent competes for the same CPU quota as its workers, so a
        # 2-CPU box measures ~parity (workers + parent > quota) while
        # the ISSUE 5 acceptance's >= 2x is a >= 4-core claim.  Judge
        # this record against cpu_count, and refresh it on real serving
        # hardware like every other measured default.
        "note": ("pool speedup is bounded by cpu_count minus the "
                 "parent's share; >= 2x requires >= 4 cores"),
        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
    }


def load_pinned(length: int) -> dict | None:
    """The committed record, iff it matches this machine and workload."""
    try:
        with open(BASELINE_PATH) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(rec, dict):
        return None
    if rec.get("machine") != machine_key():
        return None
    if rec.get("workload") != workload_key(length):
        return None
    s = rec.get("host_s_per_problem")
    if not isinstance(s, (int, float)) or s <= 0:
        return None
    return rec


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--length", type=int, default=48)
    ap.add_argument("--sample-n", type=int, default=24)
    ap.add_argument("--passes", type=int, default=30)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--pool", action="store_true",
        help="measure the hostpool 1-vs-N speedup row instead of the "
        "serial denominator (writes hostpool_baseline.json)")
    ap.add_argument("--batch-n", type=int, default=256,
                    help="batch size for the --pool measurement")
    ap.add_argument("--workers", type=int, default=None,
                    help="N for the --pool measurement (default "
                    "min(cpu_count, 8))")
    a = ap.parse_args()
    if a.pool:
        rec = measure_pool(length=a.length, batch_n=a.batch_n,
                           workers=a.workers)
        out = a.out or HOSTPOOL_PATH
    else:
        rec = measure(length=a.length, sample_n=a.sample_n,
                      passes=a.passes)
        out = a.out or BASELINE_PATH
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
