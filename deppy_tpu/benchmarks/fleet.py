"""Fleet-routing benchmark (ISSUE 15): affinity vs round-robin.

The fleet's whole claim is that warm state is worth preserving across
replicas: PR 9/14 made the in-process warm tier worth 3.9-6.7x, and a
load balancer that ignores it re-cold-solves every churn delta on
whichever replica it happens to pick.  This workload measures exactly
that: a 3-replica in-process fleet behind the router, a sustained
mixed-family churn replay (every round mutates ONE bundle of each
family — the one-row delta shape the incremental tier warm-serves),
run twice — once with the affinity ring, once with the round-robin
baseline policy — and reports per-pass p99, throughput, and the
fleet-wide warm-hit ratio (exact-cache hits + incremental warm serves
over total asks, scraped from every replica's ``/metrics``).

Under affinity each family's stream stays on one replica, so every
ask after the first is a warm serve (ratio → (rounds-1)/rounds).
Under round-robin a replica sees a family every Nth round, by which
time N bundles have churned — past the warm-cone cutoff — so nearly
every ask cold-solves.  Responses are asserted identical between the
passes (fresh replicas per pass; same documents, same answers).

Emits one JSON record in the bench.py contract: ``value`` the affinity
pass's query p99 in ms, ``vs_baseline`` the round-robin/affinity p99
ratio, plus both warm-hit ratios and the identity verdict.
``--out`` writes the full artifact (benchmarks/results/fleet_r15.json).
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict, List, Optional

from .harness import log


def _family_doc(name: str, tgts: Dict[int, int], bundles: int,
                size: int) -> dict:
    """One family's current catalog state: ``bundles`` disconnected
    dependency chains; ``tgts[b]`` is bundle ``b``'s churned mid-chain
    dependency target."""
    variables = []
    for b in range(bundles):
        for j in range(size):
            cons = []
            if j == 0:
                cons.append({"type": "mandatory"})
                cons.append({"type": "dependency",
                             "ids": [f"{name}b{b}v1"]})
            elif j == 1:
                cons.append({"type": "dependency",
                             "ids": [f"{name}b{b}v{tgts.get(b, 2)}"]})
            elif j < size - 1:
                cons.append({"type": "dependency",
                             "ids": [f"{name}b{b}v{j + 1}"]})
            variables.append({"id": f"{name}b{b}v{j}",
                              "constraints": cons})
    return {"variables": variables}


def _mutate(tgts: Dict[int, int], rnd: int, bundles: int,
            size: int) -> None:
    """Round ``rnd``'s churn: rotate ONE bundle's dependency target —
    a one-row delta whose touched cone is that bundle alone."""
    b = rnd % bundles
    tgts[b] = 2 + (tgts.get(b, 2) - 2 + 1) % (size - 2)


def _request(port: int, method: str, path: str, body=None):
    from http.client import HTTPConnection

    conn = HTTPConnection("127.0.0.1", port, timeout=60)
    headers = {"Content-Type": "application/json"} \
        if body is not None else {}
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers=headers)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def _metric(text: str, name: str) -> float:
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            total += float(line.rsplit(" ", 1)[1])
    return total


def replay(tag: str, n_families: int, rounds: int, bundles: int,
           size: int, policy: str = "affinity") -> dict:
    """One full pass: fresh 3-replica fleet + router under ``policy``,
    churn replay, fleet-wide warm accounting.  ``tag`` prefixes every
    identifier so repeat passes stay fingerprint-disjoint."""
    from ..fleet import Router
    from ..service import Server
    from ..telemetry import percentile

    replicas = [Server(bind_address="127.0.0.1:0",
                       probe_address="127.0.0.1:0", backend="host",
                       replica=f"{tag}{i}")
                for i in range(3)]
    for srv in replicas:
        srv.start()
    router = Router(
        bind_address="127.0.0.1:0",
        replicas=[f"127.0.0.1:{s.api_port}" for s in replicas],
        policy=policy)
    router.start()
    try:
        states: List[Dict[int, int]] = [dict() for _ in range(n_families)]
        latencies: List[float] = []
        rendered: List = []
        t_pass = time.perf_counter()
        for rnd in range(rounds):
            for f in range(n_families):
                if rnd:
                    _mutate(states[f], rnd - 1, bundles, size)
                doc = _family_doc(f"{tag}.f{f}.", states[f],
                                  bundles, size)
                t0 = time.perf_counter()
                status, body = _request(router.api_port, "POST",
                                        "/v1/resolve", doc)
                latencies.append(time.perf_counter() - t0)
                if status != 200:
                    raise RuntimeError(
                        f"{policy} pass: HTTP {status}: {body[:200]!r}")
                rendered.append(json.loads(body)["results"])
        wall = time.perf_counter() - t_pass
        warm = asks = 0.0
        for srv in replicas:
            _, m = _request(srv.api_port, "GET", "/metrics")
            text = m.decode()
            warm += _metric(text, "deppy_cache_hits_total") \
                + _metric(text, "deppy_incremental_hits_total")
            asks += _metric(text, "deppy_cache_hits_total") \
                + _metric(text, "deppy_cache_misses_total")
        lat = sorted(latencies)
        return {
            "policy": policy,
            "queries": len(latencies),
            "p50_ms": round(percentile(lat, 50) * 1e3, 3),
            "p99_ms": round(percentile(lat, 99) * 1e3, 3),
            "wall_s": round(wall, 3),
            "rate": round(len(latencies) / max(wall, 1e-9), 1),
            "warm_hit_ratio": round(warm / max(asks, 1.0), 4),
            "rendered": rendered,
        }
    finally:
        router.shutdown()
        for srv in replicas:
            srv.shutdown()


def _normalize(rendered, policy: str) -> str:
    return json.dumps(rendered, sort_keys=True).replace(
        f"{policy}.", "")


def run(n_families: int = 7, rounds: int = 12, bundles: int = 6,
        size: int = 6, passes: int = 2,
        out_path: Optional[str] = None) -> dict:
    if n_families % 3 == 0:
        # A family count divisible by the replica count DEGENERATES
        # round-robin into accidental perfect affinity (family f's
        # global ask counter is always ≡ f mod 3), which would report
        # the baseline as warm and the comparison as noise.  No silent
        # caps: say so and fix it.
        log(f"bumping --n-families {n_families} -> {n_families + 1} "
            f"(multiples of the 3-replica fleet alias round-robin "
            f"onto affinity)")
        n_families += 1
    log(f"fleet workload: {n_families} families x {rounds} churn "
        f"rounds over a {bundles}x{size} bundle catalog, 3 replicas, "
        f"affinity vs round-robin, {passes} passes (min-p99 kept)")
    results = {}
    for policy in ("affinity", "roundrobin"):
        best = None
        for p in range(passes):
            tag = f"p{p}.{policy}"  # per-pass prefixes: fresh servers
            #                          per pass, but keep passes
            #                          fingerprint-disjoint anyway
            r = replay(tag, n_families, rounds, bundles, size,
                       policy=policy)
            r["normalized"] = _normalize(r.pop("rendered"), tag)
            log(f"  {policy} pass {p}: p99 {r['p99_ms']}ms  warm-hit "
                f"{r['warm_hit_ratio']}  rate {r['rate']}/s")
            if best is None or r["p99_ms"] < best["p99_ms"]:
                best = r
        results[policy] = best
    identical = (results["affinity"]["normalized"]
                 == results["roundrobin"]["normalized"])
    for r in results.values():
        r.pop("normalized")
    aff, rr = results["affinity"], results["roundrobin"]
    record = {
        "metric": ("fleet churn query p99 ms "
                   "(affinity routing vs round-robin)"),
        "value": aff["p99_ms"],
        "unit": "ms",
        "vs_baseline": round(rr["p99_ms"] / max(aff["p99_ms"], 1e-9),
                             2),
        "workload": "fleet",
        "n_replicas": 3,
        "queries_per_pass": aff["queries"],
        "warm_hit_ratio_affinity": aff["warm_hit_ratio"],
        "warm_hit_ratio_roundrobin": rr["warm_hit_ratio"],
        "responses_identical": identical,
        "affinity": aff,
        "roundrobin": rr,
        "backend": "host",
    }
    if out_path:
        import os
        import platform

        full = {
            "issue": 15,
            "record": "fleet_r15",
            "platform": {
                "python": platform.python_version(),
                "machine": platform.machine(),
                "cpus": os.cpu_count(),
                "jax_platforms": (os.environ.get("JAX_PLATFORMS")
                                  or "(default)"),
            },
            "note": ("3 in-process replicas behind the fleet router, "
                     "sustained one-row-delta churn over disconnected-"
                     "bundle families; warm_hit_ratio = fleet-wide "
                     "(exact cache hits + incremental warm serves) / "
                     "asks scraped from every replica.  The affinity "
                     "acceptance is warm-hit >= 0.9 with round-robin "
                     "materially lower; absolute p99s on this box are "
                     "host-engine CPU numbers."),
            "result": record,
        }
        d = os.path.dirname(out_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(full, fh, indent=1)
            fh.write("\n")
        log(f"wrote {out_path}")
    return record


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-families", type=int, default=7)
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--bundles", type=int, default=6)
    ap.add_argument("--size", type=int, default=6)
    ap.add_argument("--out", default=None,
                    help="write the full artifact JSON here "
                    "(benchmarks/results/fleet_r15.json)")
    args = ap.parse_args()
    record = run(n_families=args.n_families, rounds=args.rounds,
                 bundles=args.bundles, size=args.size,
                 out_path=args.out)
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
