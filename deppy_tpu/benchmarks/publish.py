"""Publish-churn benchmark (ISSUE 14): speculative pre-resolution on vs off.

Production churn is push-shaped: one catalog publish fans out to many
dependent clients who all re-ask within minutes.  This workload replays
that traffic shape through the scheduler serving path as a sustained
mixed publish+query load — rounds of (catalog publish → every client
family re-asks its post-publish problem) — twice: once with the
speculative tier on (the publish queues idle-priority pre-solves, so
the re-asks land as exact cache hits), once with it off (the first
asker per family pays the solve, warm-started off the incremental
index where certifiable — the pre-speculation serving path).  Both
passes pay the full request cost (encode, canonical fingerprint,
submit) per query, so the reported p99 is end-to-end.

Pass isolation: every identifier carries a per-phase prefix
(``on.`` / ``off.``), so the two passes share NO fingerprints or
vocabulary and cannot contaminate each other through the result cache
or the clause-set index (the known churn-bench hazard); responses are
compared after stripping the prefix.

Emits one JSON record in the bench.py contract: ``value`` the
speculation-on query p99 in milliseconds, ``vs_baseline`` the off/on
p99 ratio (the ≥3× acceptance), plus ``speculative_hit_ratio`` (the
≥0.9 acceptance) and the normalized-response identity verdict.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import List, Optional

from .harness import log

DRAIN_TIMEOUT_S = 60.0
# After the queued-lane gauge reaches zero the LAST speculative flush
# may still be solving; one settle beat covers it (a straggler only
# costs the hit ratio, never correctness).
DRAIN_SETTLE_S = 0.25


def catalog_family(phase: str, family: int,
                   n_bundles: int, bundle_size: int) -> list:
    """One client family's INITIAL catalog state.  All families share
    one vocabulary (the phase-prefixed bundle ids — warm starts and
    affected-fingerprint enumeration need comparable row keys) and
    differ in preference order: bit ``b`` of ``family`` flips bundle
    ``b``'s v1 candidate order, giving ``2**n_bundles`` distinct
    fingerprints of identical shape.  Later states are produced by
    applying round deltas, exactly as a real client tracks publishes."""
    from .. import sat

    def vid(b: int, j: int) -> str:
        return f"{phase}.b{b}v{j}"

    vs = []
    for b in range(n_bundles):
        for j in range(bundle_size):
            cons = []
            if j == 0:
                cons.append(sat.mandatory())
                cons.append(sat.dependency(vid(b, 1)))
            elif j == 1:
                lo, hi = ((2, 3) if (family >> b) & 1 == 0 else (3, 2))
                cons.append(sat.dependency(vid(b, lo), vid(b, hi)))
            elif j < bundle_size - 2:
                cons.append(sat.dependency(
                    vid(b, j + 1), vid(b, min(j + 2, bundle_size - 1))))
            vs.append(sat.variable(vid(b, j), *cons))
    return vs


def round_delta(phase: str, rnd: int, n_bundles: int, bundle_size: int):
    """The round-``rnd`` catalog publish: an ABSOLUTE replacement of
    bundle ``rnd % n_bundles``'s v2 dependency row, always distinct
    from the initial row so every round changes every family."""
    from ..speculate import PublishDelta

    b = rnd % n_bundles
    c1 = 4 + rnd % max(bundle_size - 5, 1)
    c2 = min(c1 + 1, bundle_size - 1)
    return PublishDelta.from_doc({"updates": [{
        "id": f"{phase}.b{b}v2",
        "constraints": [{"type": "dependency",
                         "ids": [f"{phase}.b{b}v{c1}",
                                 f"{phase}.b{b}v{c2}"]}]}]})


def _drain(sched) -> float:
    """Block until the speculative backlog drains (bounded); returns
    the wait in seconds — the slack window production clients give a
    publish before re-asking."""
    t0 = time.perf_counter()
    deadline = t0 + DRAIN_TIMEOUT_S
    while sched.speculative_depth() and time.perf_counter() < deadline:
        time.sleep(0.005)
    time.sleep(DRAIN_SETTLE_S)
    return time.perf_counter() - t0


def replay(phase: str, speculate: bool, n_families: int, rounds: int,
           n_bundles: int, bundle_size: int) -> dict:
    """One full pass: warm-up queries, then ``rounds`` of publish (on
    pass only) + every family re-asking its post-publish problem
    through ``Scheduler.submit`` — the serving path."""
    from ..sched.scheduler import Scheduler
    from ..telemetry import percentile

    sched = Scheduler(backend="host",
                      speculate="on" if speculate else "off")
    sched.start()
    try:
        families = [catalog_family(phase, f, n_bundles, bundle_size)
                    for f in range(n_families)]
        for fam in families:  # warm-up: seed cache/index/retention
            sched.submit([fam])
        latencies: List[float] = []
        hits = 0
        rendered: List[dict] = []
        drain_s = 0.0
        t_pass = time.perf_counter()
        for rnd in range(rounds):
            delta = round_delta(phase, rnd, n_bundles, bundle_size)
            if speculate:
                sched.speculate.publish(delta)
                drain_s += _drain(sched)
            for f in range(n_families):
                applied = delta.apply(families[f])
                if applied is not None:
                    families[f] = list(applied)
                stats: dict = {}
                t0 = time.perf_counter()
                (res,) = sched.submit([families[f]], stats=stats)
                latencies.append(time.perf_counter() - t0)
                if stats.get("steps", 0) == 0 \
                        and stats.get("report") is None:
                    hits += 1  # served without any engine work
                from .. import io as problem_io

                rendered.append(problem_io.result_to_dict(res))
        wall = time.perf_counter() - t_pass
        lat_sorted = sorted(latencies)
        return {
            "queries": len(latencies),
            "p50_ms": round(percentile(lat_sorted, 50) * 1e3, 3),
            "p99_ms": round(percentile(lat_sorted, 99) * 1e3, 3),
            "hit_ratio": round(hits / max(len(latencies), 1), 4),
            "wall_s": round(wall, 3),
            "drain_wait_s": round(drain_s, 3),
            "rendered": rendered,
        }
    finally:
        sched.stop()


def _normalize(rendered: List[dict], phase: str) -> str:
    """Phase-prefix-free canonical JSON of one pass's responses — the
    per-phase request ids keep the passes cache-isolated, so identity
    is asserted modulo the prefix."""
    return json.dumps(rendered, sort_keys=True).replace(f"{phase}.", "")


def run(n_families: int = 16, rounds: int = 5, n_bundles: int = 8,
        bundle_size: int = 16, passes: int = 2,
        out_path: Optional[str] = None) -> dict:
    distinct = 2 ** n_bundles
    if n_families > distinct:
        # No silent caps: catalog_family has 2**n_bundles distinct
        # preference patterns; aliased families would be exact cache
        # hits in BOTH passes and quietly dilute the off-pass p99.
        log(f"clamping --n-families {n_families} -> {distinct} "
            f"(2**n_bundles distinct fingerprints)")
        n_families = distinct
    log(f"publish workload: {n_families} client families, {rounds} "
        f"publish rounds, {n_bundles}x{bundle_size} bundle catalog, "
        f"{passes} passes/phase (min-p99 kept)")
    results = {}
    for phase, speculate in (("off", False), ("on", True)):
        best = None
        for p in range(passes):
            tag = f"{phase}{p}"  # per-pass ids: repeat passes must not
            #                      hit the prior pass's scheduler cache
            r = replay(tag, speculate, n_families, rounds, n_bundles,
                       bundle_size)
            r["normalized"] = _normalize(r.pop("rendered"), tag)
            log(f"  {phase} pass {p}: p99 {r['p99_ms']}ms  p50 "
                f"{r['p50_ms']}ms  hits {r['hit_ratio']}")
            if best is None or r["p99_ms"] < best["p99_ms"]:
                best = r
        results[phase] = best
    identical = results["on"]["normalized"] == results["off"]["normalized"]
    for r in results.values():
        r.pop("normalized")
    on_p99 = results["on"]["p99_ms"]
    off_p99 = results["off"]["p99_ms"]
    record = {
        "metric": ("publish-churn query p99 ms "
                   "(speculative pre-resolution on vs off)"),
        "value": on_p99,
        "unit": "ms",
        "vs_baseline": round(off_p99 / max(on_p99, 1e-9), 2),
        "workload": "publish",
        "n_families": n_families,
        "rounds": rounds,
        "queries_per_pass": results["on"]["queries"],
        "speculative_hit_ratio": results["on"]["hit_ratio"],
        "responses_identical": identical,
        "off": results["off"],
        "on": results["on"],
        "backend": "host",
    }
    if out_path:
        import os
        import platform

        full = {
            "issue": 14,
            "record": "speculate_r14",
            "platform": {
                "python": platform.python_version(),
                "machine": platform.machine(),
                "cpus": os.cpu_count(),
                "jax_platforms": (os.environ.get("JAX_PLATFORMS")
                                  or "(default)"),
            },
            "note": ("sustained publish+query replay through the "
                     "scheduler serving path, host backend; per-phase "
                     "request-id prefixes isolate the on/off passes "
                     "from each other's cache (the churn-bench "
                     "hazard); min-p99-of-passes on the noisy 2-CPU "
                     "box; responses compared prefix-normalized"),
            **record,
        }
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(full, fh, indent=1)
            fh.write("\n")
        log(f"wrote {out_path}")
    return record


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-families", type=int, default=16)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--bundles", type=int, default=8)
    ap.add_argument("--bundle-size", type=int, default=16)
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--out", default=None,
                    help="also write the full record (the benchmarks/"
                    "results/speculate_r14.json artifact)")
    args = ap.parse_args()
    record = run(n_families=args.n_families, rounds=args.rounds,
                 n_bundles=args.bundles, bundle_size=args.bundle_size,
                 passes=args.passes, out_path=args.out)
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
