"""Hard-instance benchmark (ISSUE 13): portfolio racing vs fixed backends.

The hard/adversarial scenario class the ROADMAP says the single-engine
scheduler serves worst: DEEP implication chains.  The device engine's
lockstep minimization pays max-over-lanes trips that grow superlinearly
with chain depth (measured on this box: 0.15s → 1.2s → 11s per
16-lane batch at depths 192/384/768), the serial host engine pays an
O(extras²) propagation-round sweep per lane, while the certified
gradient-relaxation entrant stays one descent plus one BCP fixpoint
per lane (linear in depth).  No single backend wins every depth — the
racing scheduler takes the first definitive finisher per flush.  The
generator is the deep-implication-chain family promoted from
``scripts/bcp_ab.py`` (ISSUE 13 satellite), pinned here so the
scenario has a reproducible bench record.

Variants over the same chain list through the scheduler serving path
(cache and incremental tier off — repeat passes must measure engines,
not the result cache):

  * ``device`` — racing off, tensor backend (the canonical engine);
  * ``host``   — racing off, host backend, measured on the SHALLOWEST
    depth's lanes only (deeper lanes are strictly slower, so the
    reported rate is an optimistic upper bound — the full list would
    take minutes per pass);
  * ``race``   — portfolio racing ON (top-3: device, host, grad_relax).

Emits one JSON record in the bench.py contract: ``value`` = racing-on
throughput, ``vs_baseline`` = racing-on over the BEST fixed backend
(the ≥1.5× acceptance), with racing-on vs racing-off byte-identity
asserted in-run and recorded.  ``--out`` additionally writes the full
record (the ``benchmarks/results/portfolio_r13.json`` artifact).
"""

from __future__ import annotations

import argparse
import json
import time
from typing import List, Optional

from .harness import log

DEPTHS = (192, 384, 768)


def chain_requests(depths=DEPTHS, lanes_per_depth: int = 8
                   ) -> List[list]:
    """Deep implication chains at several depths (distinct trip counts
    — one straggler depth pins a lockstep batch): ``a0`` mandatory,
    each ``a_i`` depends on ``a_{i+1}``; every instance solves by pure
    propagation but pays a depth-long implication walk, and its
    minimal model is the whole chain (minimization cannot drop a
    link)."""
    from .. import sat

    out = []
    for depth in depths:
        vs = [sat.variable("a0", sat.mandatory(), sat.dependency("a1"))]
        vs += [sat.variable(f"a{i}", sat.dependency(f"a{i + 1}"))
               for i in range(1, depth - 1)]
        vs += [sat.variable(f"a{depth - 1}")]
        out += [vs] * lanes_per_depth
    return out


def _render(results) -> List[dict]:
    from .. import io as problem_io

    return [problem_io.result_to_dict(r) for r in results]


def _variant(requests, passes: int, **sched_kwargs):
    """Min-of-passes throughput for one scheduler configuration (the
    2-CPU-box methodology every bench row uses), plus the rendered
    results for the byte-identity pin.  Cache and incremental tier are
    OFF: repeated passes over an identical problem list would
    otherwise measure the result cache, not the engines racing."""
    from ..sched.scheduler import Scheduler

    from ..sched import scheduler as _sched_mod

    sched = Scheduler(cache_size=0, incremental="off", **sched_kwargs)
    results = sched.submit(requests)  # warm-up: compiles, first-touch
    walls = []
    for _ in range(passes):
        # Quiesce abandoned race losers (a cancelled device program
        # runs out its dispatch in the background) so each pass pays
        # its own race, not the previous pass's stragglers.
        _sched_mod._join_race_threads()
        t0 = time.perf_counter()
        results = sched.submit(requests)
        walls.append(time.perf_counter() - t0)
    best = min(walls)
    _sched_mod._join_race_threads()
    return {
        "n_problems": len(requests),
        "wall_s_passes": [round(w, 4) for w in walls],
        "wall_s_min": round(best, 4),
        "problems_per_s_min_pass": round(len(requests) / best, 1),
    }, _render(results)


def run(lanes_per_depth: int = 8, passes: int = 2,
        out_path: Optional[str] = None) -> dict:
    requests = chain_requests(lanes_per_depth=lanes_per_depth)
    log(f"hard workload: {len(requests)} deep-implication-chain lanes "
        f"(depths {DEPTHS} x {lanes_per_depth})")

    variants = {}
    log("variant device (racing off, tensor backend)...")
    variants["device"], ref = _variant(
        requests, passes, backend="tpu", portfolio="off")
    log(f"variant host (racing off, host backend; shallowest depth "
        f"only — upper bound)...")
    variants["host"], _ = _variant(
        requests[:lanes_per_depth], passes, backend="host",
        portfolio="off")
    variants["host"]["upper_bound"] = True
    log("variant race (portfolio on, k=3)...")
    variants["race"], race_res = _variant(
        requests, passes, backend="tpu", portfolio="on", portfolio_k=3,
        portfolio_sample_check=0.0)

    identical = race_res == ref
    best_fixed = max(variants["device"]["problems_per_s_min_pass"],
                     variants["host"]["problems_per_s_min_pass"])
    race_rate = variants["race"]["problems_per_s_min_pass"]
    record = {
        "metric": ("hard-instance resolutions/sec "
                   "(portfolio race vs best fixed backend)"),
        "value": race_rate,
        "unit": "problems/s",
        "vs_baseline": round(race_rate / best_fixed, 3) if best_fixed
        else 0.0,
        "workload": "hard",
        "n_problems": len(requests),
        "race_identical_to_off": identical,
        "best_fixed_backend": ("device"
                               if variants["device"]
                               ["problems_per_s_min_pass"] >= variants
                               ["host"]["problems_per_s_min_pass"]
                               else "host"),
        "variants": variants,
    }
    if out_path:
        import os
        import platform

        full = {
            "issue": 13,
            "record": "portfolio_r13",
            "platform": {
                "python": platform.python_version(),
                "machine": platform.machine(),
                "cpus": os.cpu_count(),
                "jax_platforms": (os.environ.get("JAX_PLATFORMS")
                                  or "(default)"),
            },
            "note": ("forced-CPU hard-instance A/B; min-of-passes "
                     "(2-CPU box, timing noisy); race = "
                     "device/host/grad_relax top-3, first definitive "
                     "finisher wins, byte-identity to racing-off "
                     "asserted in-run; the host row measures the "
                     "shallowest depth only (optimistic upper bound "
                     "— deeper lanes are strictly slower)"),
            **record,
        }
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(full, fh, indent=1)
            fh.write("\n")
        log(f"wrote {out_path}")
    return record


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--lanes-per-depth", type=int, default=8)
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--out", default=None,
                    help="also write the full A/B record (the "
                    "benchmarks/results/portfolio_r13.json artifact)")
    args = ap.parse_args()
    record = run(lanes_per_depth=args.lanes_per_depth,
                 passes=args.passes, out_path=args.out)
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
