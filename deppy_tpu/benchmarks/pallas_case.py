"""The Pallas kernel's target workload: ONE giant catalog problem.

The fused VMEM-resident fixpoint kernel (:mod:`deppy_tpu.engine.pallas_bcp`)
loses to the vmapped jnp "bits" path on batched workloads — XLA vectorizes
the batch axis across the VPU lanes — and is predicted by its own docstring
to win only on a single problem whose clause planes approach VMEM capacity,
where each propagation round's HBM re-streaming is the bottleneck.  This
benchmark builds exactly that case — the default 250 packages × 8 versions
is a ~2k-bundle catalog whose padded plane dims sit just under the
kernel's VMEM caps (C ≤ 8192, Wv ≤ 128; see pallas_bcp.py) — and
measures ``bits`` vs ``pallas`` on it.

Run on TPU: ``python -m deppy_tpu.benchmarks.pallas_case``.
Prints one JSON line per impl and a final comparison line; feeds the
"earn the Pallas kernel's keep" row of BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import time

from .harness import log


def _build(n_packages: int, versions: int):
    from ..models import operatorhub_catalog
    from ..sat.encode import encode

    t0 = time.perf_counter()
    p = encode(operatorhub_catalog(
        n_packages=n_packages, versions_per_package=versions, seed=0
    ))
    log(f"encode: {time.perf_counter() - t0:.2f}s — n_vars={p.n_vars} "
        f"n_cons={p.n_cons} clauses={p.clauses.shape}")
    return p


def _measure(problem, impl: str, repeats: int) -> dict:
    from ..engine import core, driver

    core.set_bcp_impl(impl)
    try:
        t0 = time.perf_counter()
        (res,) = driver.solve_problems([problem])
        warm_s = time.perf_counter() - t0
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            (res,) = driver.solve_problems([problem])
            times.append(time.perf_counter() - t0)
        best = min(times)
        rec = {
            "impl": impl,
            "solve_ms": round(best * 1e3, 2),
            "rate": round(1.0 / best, 2),
            "warmup_s": round(warm_s, 2),
            "outcome": int(res.outcome),
            "steps": int(res.steps),
        }
    finally:
        core.set_bcp_impl("auto")
    return rec


def _append_log(rec: dict, log_path: str) -> None:
    if log_path:
        with open(log_path, "a") as f:
            f.write(json.dumps(rec) + "\n")


def run(n_packages: int, versions: int, repeats: int,
        impls: "list | None" = None, log_path: str = "") -> list:
    import jax

    backend = jax.default_backend()
    log(f"jax backend: {backend} devices={jax.devices()}")
    problem = _build(n_packages, versions)

    # Respect the kernel's VMEM budget (pallas_bcp.py docstring): the
    # dominant planes are 2*C*Wv int32 words.
    from ..engine.driver import _Dims

    d = _Dims([problem], 1)
    vmem_mb = 2 * d.C * d.Wv * 4 / 2**20
    log(f"padded dims: C={d.C} V={d.V} Wv={d.Wv} -> clause planes "
        f"{vmem_mb:.1f} MiB in VMEM")

    if impls is None:
        impls = ["bits", "pallas"] if backend == "tpu" else ["bits"]
        if backend != "tpu":
            log("pallas requires the TPU backend; measuring bits only")
    out = []
    for impl in impls:
        rec = _measure(problem, impl, repeats)
        print(json.dumps(rec), flush=True)
        # Per-record, not end-of-run: a later (riskier) impl wedging the
        # worker must not cost the safe measurement already completed —
        # the same reason the revalidation ladder orders its stages
        # safest-first.
        _append_log(rec, log_path)
        out.append(rec)
    if len(out) >= 2:
        base = out[0]
        for rec in out[1:]:
            cmp = {
                "metric": (f"single giant catalog solve, {rec['impl']} "
                           f"vs {base['impl']}"),
                f"{base['impl']}_ms": base["solve_ms"],
                f"{rec['impl']}_ms": rec["solve_ms"],
                "speedup": round(base["solve_ms"] / rec["solve_ms"], 3),
                "agree": rec["outcome"] == base["outcome"],
            }
            print(json.dumps(cmp), flush=True)
            _append_log(cmp, log_path)
            out.append(cmp)
    return out


def main() -> None:
    from ..utils.platform_env import apply_platform_env

    apply_platform_env()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--packages", type=int, default=250)
    ap.add_argument("--versions", type=int, default=8)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--impls", default="",
                    help="comma-separated impl list (default: bits,pallas "
                    "on TPU).  The over-VMEM case is 'bits,blockwise' at "
                    "--packages 1000+ (clause planes 2-4x the fixpoint "
                    "kernel's VMEM cap; engine/pallas_blockwise.py)")
    ap.add_argument("--log", default="",
                    help="also append each record as a JSON line here "
                    "(the revalidation ladder passes its own log so the "
                    "measurement survives the stage)")
    args = ap.parse_args()
    run(args.packages, args.versions, args.repeats,
        impls=[s.strip() for s in args.impls.split(",") if s.strip()]
        or None, log_path=args.log)


if __name__ == "__main__":
    main()
