"""Benchmark harnesses.

The reference's only performance harness is ``go test -bench`` over a
seeded random instance (/root/reference/pkg/sat/bench_test.go:10-19,66-86)
and it publishes no numbers (SURVEY.md §6).  This package holds the
rebuild's measured equivalents:

  * :mod:`deppy_tpu.benchmarks.headline` — the driver-facing headline
    metric (batched catalog resolutions/sec, device vs serial host);
  * :mod:`deppy_tpu.benchmarks.suite` — all five BASELINE.json workload
    configs, host vs device, for BASELINE.md.
"""
