"""Upgrade-planning benchmark (ISSUE 18): warm vs cold bound-tightening.

Production upgrade traffic is churn-shaped: a catalog publish makes a
few packages prefer newer bundles, and the operator asks for the
minimal-change plan — newest acceptable bundles, fewest installed
entities touched.  This workload replays that shape through the
serving path (``Planner`` riding ``Scheduler.submit_optimize``) as
rounds of upgrade queries over a churned bundle catalog: each round
rotates which packages drift toward newer versions, so the
preference-ordered feasibility solve over-upgrades and the tightening
loop must walk the touch count back down to the minimum.

Two passes answer the same rounds: one with warm cone probes
(``warm: true`` — off-cone variables pinned to the previous model's
phases, so a probe only re-searches where an improvement can come
from) and one forced cold (every probe searches the full catalog).
Per-probe durations come from the telemetry sink's ``optimize``
events alone — the same stream ``deppy profile`` renders — keyed by
the per-pass tenant label, so the two passes cannot contaminate each
other's numbers.

Emits one JSON record in the bench.py contract: ``value`` the warm
pass's mean microseconds per tightening probe, ``vs_baseline`` the
cold-to-warm per-probe ratio (the >= 3x acceptance), plus
iterations-to-optimum and the objective-identity verdict (both passes
must prove the same optimum).
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from .harness import log


def upgrade_catalog(n_packages: int,
                    catalog_versions: Dict[int, List[str]]) -> list:
    """One round's bundle catalog.  Package ``p`` is a version group
    (AtMost-1 pin) whose versions each depend on the next package,
    chaining the whole catalog under one mandatory root.  Each
    package's dependency row lists its versions NEWEST FIRST — the real
    catalog's preference order — so the preference-ordered feasibility
    solve upgrades EVERY package, and the tightening loop earns its
    keep walking the touch count back to the minimal-change plan."""
    from .. import sat

    variables = []
    for p in range(n_packages):
        vids = catalog_versions[p]
        cons = [sat.dependency(*vids), sat.at_most(1, *vids)]
        if p == 0:
            cons.insert(0, sat.mandatory())
        variables.append(sat.variable(f"p{p}", *cons))
        for vid in vids:
            vcons = []
            if p + 1 < n_packages:
                vcons.append(sat.dependency(f"p{p + 1}"))
            variables.append(sat.variable(vid, *vcons))
    return variables


def round_docs(n_packages: int, versions: int, rounds: int,
               n_drift: int) -> List[dict]:
    """The benchmark's request stream: one upgrade document per round
    over a CHURNED catalog.  Each round, a rotating window of
    ``n_drift`` packages ships a new release (a new bundle id,
    inserted at the head of its package's preference row) that the
    round's plan must adopt (``prefer``); the installed state carries
    the minimal-change plan forward round to round, exactly as a
    cluster tracks its own upgrade history."""
    from .. import io as problem_io

    # catalog_versions[p] is newest-first; installed[p] the running
    # cluster state (initially the OLDEST bundle of every package).
    catalog_versions = {
        p: [f"p{p}.v{v}" for v in range(versions)]
        for p in range(n_packages)}
    installed = {p: f"p{p}.v{versions - 1}" for p in range(n_packages)}
    docs = []
    for rnd in range(rounds):
        drift = sorted((rnd * n_drift + i) % n_packages
                       for i in range(n_drift))
        prefer = []
        for p in drift:
            release = f"p{p}.r{rnd}"
            catalog_versions[p] = [release] + catalog_versions[p]
            prefer.append(release)
        variables = upgrade_catalog(n_packages, catalog_versions)
        docs.append({
            "query": "upgrade",
            "variables": [problem_io.variable_to_dict(v)
                          for v in variables],
            "installed": ([f"p{p}" for p in range(n_packages)]
                          + sorted(installed.values())),
            "prefer": prefer,
        })
        for p in drift:  # the optimal plan: adopt the release, touch
            installed[p] = f"p{p}.r{rnd}"  # nothing else
    return docs


def replay(docs: List[dict], warm: bool, tenant: str) -> dict:
    """One full pass through the serving path: every round's document
    answered by a fresh Planner probe loop on a shared scheduler."""
    from ..optimize import Planner
    from ..sched.scheduler import Scheduler

    sched = Scheduler(backend="host")
    sched.start()
    try:
        planner = Planner(sched)
        iterations = 0
        improvements = 0
        objectives: List[int] = []
        wall = 0.0
        for doc in docs:
            doc = dict(doc)
            doc["warm"] = warm
            t0 = time.perf_counter()
            out = planner.handle(doc, tenant=tenant)
            wall += time.perf_counter() - t0
            if out.get("status") != "optimal":
                raise RuntimeError(
                    f"pass {tenant}: round degraded: {out}")
            iterations += out["iterations"]
            improvements += out["improvements"]
            objectives.append(out["objective"])
        return {
            "rounds": len(docs),
            "iterations": iterations,
            "improvements": improvements,
            "iterations_per_round": round(iterations / len(docs), 2),
            "wall_s": round(wall, 3),
            "objectives": objectives,
        }
    finally:
        sched.stop()


def probe_stats(sink_path: str) -> Dict[str, dict]:
    """Per-(tenant, mode) probe counts and mean duration from the
    sink's ``optimize`` events alone — the measurement is the same
    stream ``deppy profile`` renders, not bench-side stopwatches."""
    from ..telemetry import iter_sink_events

    out: Dict[str, dict] = {}
    for ev in iter_sink_events(sink_path):
        if not isinstance(ev, dict) or ev.get("kind") != "optimize":
            continue
        key = f"{ev.get('tenant')}:{ev.get('mode')}"
        agg = out.setdefault(key, {"probes": 0, "improved": 0,
                                   "dur_s": 0.0})
        agg["probes"] += 1
        agg["dur_s"] += float(ev.get("dur_s", 0.0) or 0.0)
        if ev.get("outcome") == "improved":
            agg["improved"] += 1
    for agg in out.values():
        agg["dur_s"] = round(agg["dur_s"], 6)
        agg["us_per_probe"] = (
            round(agg["dur_s"] * 1e6 / agg["probes"], 1)
            if agg["probes"] else 0.0)
    return out


def run(n_packages: int = 96, versions: int = 4, rounds: int = 6,
        n_drift: int = 4, out_path: Optional[str] = None) -> dict:
    from .. import telemetry

    log(f"upgrade workload: {n_packages} packages x {versions} "
        f"versions ({n_packages * (versions + 1)} bundles), {rounds} "
        f"churn rounds, {n_drift} new releases/round")
    docs = round_docs(n_packages, versions, rounds, n_drift)
    sink = tempfile.mktemp(prefix="deppy_upgrade_", suffix=".jsonl")
    telemetry.configure_sink(sink)
    try:
        cold = replay(docs, warm=False, tenant="cold")
        warm = replay(docs, warm=True, tenant="warm")
    finally:
        telemetry.configure_sink(None)
    try:
        probes = probe_stats(sink)
    finally:
        try:
            os.unlink(sink)
        except OSError:
            pass
    warm_p = probes.get("warm:warm", {"probes": 0, "dur_s": 0.0})
    cold_p = probes.get("cold:cold", {"probes": 0, "dur_s": 0.0})
    # A zero-probe pass is an honest failure (value 0), not a divide.
    warm_us = (warm_p["dur_s"] / warm_p["probes"] * 1e6
               if warm_p["probes"] else 0.0)
    cold_us = (cold_p["dur_s"] / cold_p["probes"] * 1e6
               if cold_p["probes"] else 0.0)
    record = {
        "metric": ("upgrade-plan tightening us/probe "
                   "(warm cone probes vs cold full-catalog)"),
        "value": round(warm_us, 1),
        "unit": "us",
        "vs_baseline": (round(cold_us / warm_us, 2) if warm_us
                        else 0.0),
        "workload": "upgrade",
        "n_packages": n_packages,
        "versions": versions,
        "rounds": rounds,
        "n_drift": n_drift,
        "iterations_per_round": warm["iterations_per_round"],
        "warm_probe_us": round(warm_us, 1),
        "cold_probe_us": round(cold_us, 1),
        "warm_hit_ratio": round(
            warm_p.get("improved", 0) / max(warm_p["probes"], 1), 4),
        "objectives_identical": warm["objectives"] == cold["objectives"],
        "cold": cold,
        "warm": warm,
        "probes": probes,
        "backend": "host",
    }
    if out_path:
        import platform

        full = {
            "issue": 18,
            "record": "upgrade_r18",
            "platform": {
                "python": platform.python_version(),
                "machine": platform.machine(),
                "cpus": os.cpu_count(),
                "jax_platforms": (os.environ.get("JAX_PLATFORMS")
                                  or "(default)"),
            },
            "note": ("churned-catalog upgrade rounds through the "
                     "scheduler serving path, host backend; per-probe "
                     "durations from the telemetry sink's `optimize` "
                     "events keyed by per-pass tenant (the stream "
                     "`deppy profile` renders); both passes must prove "
                     "the same optimum per round"),
            **record,
        }
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(full, fh, indent=1)
            fh.write("\n")
        log(f"wrote {out_path}")
    return record


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-packages", type=int, default=96)
    ap.add_argument("--versions", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--drift", type=int, default=4)
    ap.add_argument("--out", default=None,
                    help="also write the full record (the benchmarks/"
                    "results/upgrade_r18.json artifact)")
    args = ap.parse_args()
    record = run(n_packages=args.n_packages, versions=args.versions,
                 rounds=args.rounds, n_drift=args.drift,
                 out_path=args.out)
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
