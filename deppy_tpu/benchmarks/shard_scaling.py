"""Sharded-scheduler scaling row (ISSUE 6): single-device vs full-mesh.

Measures the scheduler's dispatch path at two mesh sizes on the current
platform — 1 device (the historical single-device dispatch) and every
local device (the batch-axis sharded entry) — and writes one JSON record
to ``benchmarks/results/`` so the scaling curve is tracked per round.

On real hardware the mesh row is the paper's multi-chip claim; on the
forced-CPU 8-device platform (CI, dev boxes) the virtual devices share
the host's cores, so the row tracks *overhead parity* (the sharded path
must not cost throughput), not speedup — the record carries the
platform and core count so readers can tell which claim they are
looking at.

Usage::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python -m deppy_tpu.benchmarks.shard_scaling \
        --out benchmarks/results/shard_scaling_r06.json
"""

from __future__ import annotations

import json
import os
import time

from .harness import log, probe_wall_s


def run(n_problems: int = 512, length: int = 32,
        out: str | None = None) -> dict:
    import jax

    from ..engine import driver
    from ..models import random_instance
    from ..parallel.mesh import serving_mesh
    from ..sat.encode import encode

    probe_s = probe_wall_s()
    n_dev = len(jax.devices())
    problems = [encode(random_instance(length=length, seed=s))
                for s in range(n_problems)]

    def rate(fn) -> float:
        fn()  # warm-up (compile)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return n_problems / best

    r_single = rate(lambda: driver.solve_problems(problems))
    log(f"single-device: {r_single:.1f}/s")
    mesh = serving_mesh(-1)
    r_mesh = r_single
    if mesh is not None:
        r_mesh = rate(
            lambda: driver.solve_problems_sharded(problems, mesh=mesh))
        log(f"mesh({int(mesh.size)}): {r_mesh:.1f}/s "
            f"({r_mesh / r_single:.2f}x)")
    else:
        log("single local device: mesh row = single row")

    rec = {
        "metric": "sharded-scheduler throughput, single vs mesh",
        "unit": "problems/s",
        "backend": jax.default_backend(),
        "n_devices": n_dev,
        "cpu_count": os.cpu_count(),
        "n_problems": n_problems,
        "length": length,
        "probe_wall_s": round(probe_s, 3),
        "rows": [
            {"mesh_devices": 1, "rate": round(r_single, 2),
             "per_device_rate": round(r_single, 2)},
            {"mesh_devices": int(mesh.size) if mesh is not None else 1,
             "rate": round(r_mesh, 2),
             "per_device_rate": round(
                 r_mesh / (int(mesh.size) if mesh is not None else 1), 2)},
        ],
        "speedup": round(r_mesh / r_single, 3),
        # Virtual devices on a shared host measure dispatch overhead,
        # not chip scaling — make the record self-describing.
        "note": ("forced-CPU virtual devices share host cores: this row "
                 "tracks sharded-path overhead parity, not chip scaling"
                 ) if jax.default_backend() == "cpu" else "",
    }
    if out:
        if os.path.dirname(out):
            os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w", encoding="utf-8") as fh:
            json.dump(rec, fh, indent=2)
            fh.write("\n")
        log(f"wrote {out}")
    print(json.dumps(rec), flush=True)
    return rec


def main() -> None:
    import argparse

    from ..utils.platform_env import apply_platform_env

    apply_platform_env()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-problems", type=int, default=512)
    ap.add_argument("--length", type=int, default=32)
    ap.add_argument("--out", default=None,
                    help="also write the record to this JSON file")
    a = ap.parse_args()
    run(n_problems=a.n_problems, length=a.length, out=a.out)


if __name__ == "__main__":
    main()
