"""Soak/chaos survival gate (ISSUE 17): elastic fleet under churn.

The elastic-membership claim is not "joins work on a quiet fleet" —
it is that a fleet survives the full churn script **while serving**:
replicas crash, a new replica joins at runtime (warm-state stream,
then the atomic arc flip), a member drains out, and a router dies with
clients failing over to its peer — all under sustained open-loop
mixed-tenant load, with nothing a client can observe beyond counted
admission sheds.

The harness:

* **Fleet** — 3 in-process host-backend replicas behind TWO peered
  elastic routers (``--peers`` each other); clients prefer router 0
  and fail over to router 1 on a transport error.  A fourth,
  fleet-detached replica is the **fault-free oracle**.
* **Load** — an open-loop generator: arrivals on a fixed schedule
  (``rate`` per second), each request on its own thread (bounded
  in-flight), never waiting for the previous answer — overload shows
  up as queueing, not as a politely slowed generator.  Families are
  picked Zipf-style (weights ``1/(rank+1)^1.1``) so a hot head and a
  long warm tail coexist; ~40% of picks churn the family's catalog by
  a one-row delta first; tenants mix ``gold`` (priority lane) and
  ``bulk`` traffic.
* **Chaos script** (fractions of the run): 0.15 hard-kill a replica;
  0.35 boot a NEW replica with ``--fleet-router`` (the real announce →
  join-stream → arc-flip path) and wait for membership; 0.55 drain a
  member through ``POST /fleet/drain``; 0.75 wait for the peer router
  to gossip up to the latest epoch, then kill router 0.
* **Verdict** — the run FAILS on any of: a client-visible error
  (non-200 that is not a counted admission shed), a byte-identity
  mismatch (every k-th successful response replayed on the oracle
  after the run and compared), a shed landing on the ``gold`` tenant,
  p99 over budget, or a post-join fleet-wide warm-hit ratio under the
  floor (the join stream must actually carry the warm state — a fleet
  that cold-solves after every flip "survives" by re-doing all its
  work).

Emits one JSON record in the bench.py contract; ``--out`` writes the
full artifact (benchmarks/results/soak_r17.json).
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time
from typing import Dict, List, Optional

from .fleet import _family_doc, _metric, _mutate, _request
from .harness import log

# Client-side discrimination of 503s: the router's no-replica answer
# is a route outage (an ERROR for the gate); anything else with a 503
# status is a replica admission shed (counted per tenant, allowed for
# bulk, fatal for gold).
_OUTAGE_MARKER = b"no replica reachable"

TENANT_WEIGHTS = json.dumps({
    "gold": {"weight": 3, "priority": 0},
    "bulk": {"weight": 1, "priority": 1},
})


def _zipf_weights(n: int, s: float = 1.1) -> List[float]:
    return [1.0 / float(rank + 1) ** s for rank in range(n)]


class _Stats:
    """Thread-safe tally of everything the gate judges."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies: List[float] = []
        self.post_join_latencies: List[float] = []
        self.ok = 0
        self.errors: List[str] = []
        self.sheds: Dict[str, int] = {}
        self.failovers = 0
        self.generator_drops = 0
        self.samples: List[tuple] = []   # (doc_json, results) replays
        self.join_done_at: Optional[float] = None


def _scrape_warm(port: int) -> Optional[Dict[str, float]]:
    try:
        status, body = _request(port, "GET", "/metrics")
    except OSError:
        return None
    if status != 200:
        return None
    text = body.decode()
    return {
        "warm": _metric(text, "deppy_cache_hits_total")
        + _metric(text, "deppy_incremental_hits_total"),
        "asks": _metric(text, "deppy_cache_hits_total")
        + _metric(text, "deppy_cache_misses_total"),
    }


class SoakFleet:
    """The fleet + routers + oracle under test, and the chaos that
    befalls them."""

    def __init__(self, seconds: float, rate: float, seed: int,
                 n_families: int, bundles: int, size: int,
                 sample_every: int, max_in_flight: int):
        from ..fleet import Router
        from ..service import Server

        self.seconds = float(seconds)
        self.rate = float(rate)
        self.rnd = random.Random(seed)
        self.n_families = n_families
        self.bundles = bundles
        self.size = size
        self.sample_every = max(int(sample_every), 1)
        self.max_in_flight = max(int(max_in_flight), 1)
        self.states: List[Dict[int, int]] = [dict()
                                             for _ in range(n_families)]
        self.weights = _zipf_weights(n_families)
        self.stats = _Stats()
        self._doc_lock = threading.Lock()

        self.replicas = [
            Server(bind_address="127.0.0.1:0",
                   probe_address="127.0.0.1:0", backend="host",
                   replica=f"soak{i}", tenant_weights=TENANT_WEIGHTS)
            for i in range(3)]
        for srv in self.replicas:
            srv.start()
        addrs = [f"127.0.0.1:{s.api_port}" for s in self.replicas]
        # Two peered elastic routers.  Router 1's push loop converges
        # both directions (each /fleet/sync exchange reconciles the
        # inbound view AND answers with the local one), so router 0
        # learning its peer address post-start is bookkeeping, not a
        # gossip gap.
        self.router0 = Router(bind_address="127.0.0.1:0",
                              replicas=addrs, membership="elastic",
                              probe_interval_s=0.3, probe_failures=2,
                              sync_interval_s=0.4)
        self.router0.start()
        r0 = f"127.0.0.1:{self.router0.api_port}"
        self.router1 = Router(bind_address="127.0.0.1:0",
                              replicas=addrs, membership="elastic",
                              peers=[r0], probe_interval_s=0.3,
                              probe_failures=2, sync_interval_s=0.4)
        self.router1.start()
        self.router0.peers = [f"127.0.0.1:{self.router1.api_port}"]
        self.router_ports = [self.router0.api_port,
                             self.router1.api_port]
        self._primary = 0
        self.oracle = Server(bind_address="127.0.0.1:0",
                             probe_address="127.0.0.1:0",
                             backend="host", replica="oracle")
        self.oracle.start()
        self.joiner = None
        self._warm_base: Dict[int, Dict[str, float]] = {}
        self._warm_final: Dict[int, Dict[str, float]] = {}
        self.chaos_log: List[str] = []
        self.peer_view: Optional[dict] = None

    # ---------------------------------------------------------- client

    def _build_request(self) -> tuple:
        """Pick tenant + family, maybe churn it, render the doc.
        Serialized under one lock so churn deltas stay one-row."""
        with self._doc_lock:
            fam = self.rnd.choices(range(self.n_families),
                                   weights=self.weights)[0]
            if self.rnd.random() < 0.4:
                _mutate(self.states[fam], self.rnd.randrange(1 << 20),
                        self.bundles, self.size)
            tenant = "gold" if self.rnd.random() < 0.25 else "bulk"
            sample = (self.stats.ok + len(self.stats.errors)) \
                % self.sample_every == 0
            doc = _family_doc(f"soak.f{fam}.", self.states[fam],
                              self.bundles, self.size)
        return doc, tenant, sample

    def _post_resolve(self, port: int, doc: dict, tenant: str):
        from http.client import HTTPConnection

        conn = HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request("POST", "/v1/resolve", body=json.dumps(doc),
                         headers={"Content-Type": "application/json",
                                  "X-Deppy-Tenant": tenant})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _one_request(self, doc: dict, tenant: str, sample: bool):
        st = self.stats
        t0 = time.perf_counter()
        try:
            status, body = self._post_resolve(
                self.router_ports[self._primary], doc, tenant)
        except OSError:
            # Router down: fail over to the peer and retry once —
            # the "clients can hit any router" contract.
            self._primary = 1 - self._primary
            with st.lock:
                st.failovers += 1
            try:
                status, body = self._post_resolve(
                    self.router_ports[self._primary], doc, tenant)
            except OSError as exc:
                with st.lock:
                    st.errors.append(f"both routers unreachable: {exc}")
                return
        dt = time.perf_counter() - t0
        with st.lock:
            if status == 200:
                st.ok += 1
                st.latencies.append(dt)
                if st.join_done_at is not None:
                    st.post_join_latencies.append(dt)
                if sample:
                    st.samples.append(
                        (json.dumps(doc),
                         json.loads(body)["results"]))
            elif status == 503 and _OUTAGE_MARKER not in body:
                st.sheds[tenant] = st.sheds.get(tenant, 0) + 1
            else:
                st.errors.append(
                    f"HTTP {status} ({tenant}): {body[:160]!r}")

    def _generate(self, stop_at: float):
        """Open-loop arrivals: fixed interval, thread per request,
        never blocked by a slow server (a full in-flight window is
        counted, not waited out)."""
        interval = 1.0 / max(self.rate, 0.1)
        threads: List[threading.Thread] = []
        next_at = time.monotonic()
        while time.monotonic() < stop_at:
            now = time.monotonic()
            if now < next_at:
                time.sleep(min(next_at - now, 0.05))
                continue
            next_at += interval
            threads = [t for t in threads if t.is_alive()]
            if len(threads) >= self.max_in_flight:
                with self.stats.lock:
                    self.stats.generator_drops += 1
                continue
            doc, tenant, sample = self._build_request()
            t = threading.Thread(target=self._one_request,
                                 args=(doc, tenant, sample),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=30)

    # ----------------------------------------------------------- chaos

    def _router_doc(self, port: int, path: str) -> Optional[dict]:
        try:
            status, body = _request(port, "GET", path)
        except OSError:
            return None
        if status != 200:
            return None
        return json.loads(body)

    def _kill_replica(self):
        victim = self.replicas[2]
        addr = f"127.0.0.1:{victim.api_port}"
        victim.shutdown(drain_s=0)
        self.chaos_log.append(f"killed replica {addr}")
        log(f"  chaos: killed replica {addr}")

    def _join_replica(self, deadline_s: float = 20.0):
        from ..service import Server

        self.joiner = Server(
            bind_address="127.0.0.1:0", probe_address="127.0.0.1:0",
            backend="host", replica="joiner",
            tenant_weights=TENANT_WEIGHTS,
            fleet_router=f"127.0.0.1:{self.router0.api_port}")
        self.joiner.start()
        addr = f"127.0.0.1:{self.joiner.api_port}"
        deadline = time.monotonic() + deadline_s
        joined = False
        while time.monotonic() < deadline:
            doc = self._router_doc(self.router0.api_port,
                                   "/fleet/replicas")
            if doc and addr in doc.get("members", []):
                joined = True
                break
            time.sleep(0.2)
        if not joined:
            with self.stats.lock:
                self.stats.errors.append(
                    f"joiner {addr} never became a member")
            return
        with self.stats.lock:
            self.stats.join_done_at = time.monotonic()
        # Post-join warm-accounting baseline: every replica serving
        # from here to the end.
        for srv in (self.replicas[0], self.replicas[1], self.joiner):
            snap = _scrape_warm(srv.api_port)
            if snap is not None:
                self._warm_base[srv.api_port] = snap
        self.chaos_log.append(f"joined replica {addr}")
        log(f"  chaos: joined replica {addr} (arc flip committed)")

    def _drain_replica(self):
        victim = self.replicas[1]
        addr = f"127.0.0.1:{victim.api_port}"
        try:
            status, body = _request(
                self.router0.api_port, "POST", "/fleet/drain",
                {"replica": addr})
            if status != 200:
                with self.stats.lock:
                    self.stats.errors.append(
                        f"drain of {addr}: HTTP {status}: "
                        f"{body[:160]!r}")
        except OSError as exc:
            with self.stats.lock:
                self.stats.errors.append(f"drain of {addr}: {exc}")
        # The drained member's warm counters stop here; capture them
        # as its final word before the process goes away.
        snap = _scrape_warm(victim.api_port)
        if snap is not None:
            self._warm_final[victim.api_port] = snap
        victim.shutdown(drain_s=0)
        self.chaos_log.append(f"drained replica {addr}")
        log(f"  chaos: drained replica {addr}")

    def _kill_router(self, deadline_s: float = 10.0):
        # The peer must have gossiped up to the latest epoch before
        # the authoritative router dies, or the failover target would
        # route on a stale ring.
        want = self.router0.epoch
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            doc = self._router_doc(self.router1.api_port,
                                   "/fleet/replicas")
            if doc and doc.get("epoch", 0) >= want:
                self.peer_view = {k: doc[k] for k in
                                  ("epoch", "members", "membership")}
                break
            time.sleep(0.2)
        if self.peer_view is None:
            with self.stats.lock:
                self.stats.errors.append(
                    f"peer router never reached epoch {want}")
        self.router0.shutdown()
        self.chaos_log.append(
            f"killed router 0 at epoch {want}; peer view "
            f"{self.peer_view}")
        log(f"  chaos: killed router 0 (peer at epoch "
            f"{(self.peer_view or {}).get('epoch')})")

    def _chaos(self, t0: float):
        script = [(0.15, self._kill_replica),
                  (0.35, self._join_replica),
                  (0.55, self._drain_replica),
                  (0.75, self._kill_router)]
        for frac, action in script:
            delay = t0 + frac * self.seconds - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                action()
            except Exception as exc:  # deppy: lint-ok[exception-hygiene] — a chaos step must not silently end the script; the failure is the run's verdict
                with self.stats.lock:
                    self.stats.errors.append(
                        f"chaos step {action.__name__}: "
                        f"{type(exc).__name__}: {exc}")

    # ----------------------------------------------------------- gates

    def _replay_oracle(self) -> int:
        mismatches = 0
        for doc_json, results in self.stats.samples:
            status, body = _request(self.oracle.api_port, "POST",
                                    "/v1/resolve",
                                    json.loads(doc_json))
            if status != 200:
                mismatches += 1
                continue
            if json.loads(body)["results"] != results:
                mismatches += 1
        return mismatches

    def _warm_hit_post_join(self) -> Optional[float]:
        if not self._warm_base:
            return None
        for port, base in self._warm_base.items():
            if port in self._warm_final:
                continue
            snap = _scrape_warm(port)
            if snap is not None:
                self._warm_final[port] = snap
        warm = asks = 0.0
        for port, base in self._warm_base.items():
            final = self._warm_final.get(port)
            if final is None:
                continue
            warm += final["warm"] - base["warm"]
            asks += final["asks"] - base["asks"]
        if asks <= 0:
            return None
        return warm / asks

    def shutdown(self):
        for router in (self.router0, self.router1):
            try:
                router.shutdown()
            except Exception:  # deppy: lint-ok[exception-hygiene] — already chaos-killed routers re-shutdown on the cleanup path
                pass
        servers = [s for s in self.replicas if s is not None]
        if self.joiner is not None:
            servers.append(self.joiner)
        servers.append(self.oracle)
        for srv in servers:
            try:
                srv.shutdown(drain_s=0)
            except Exception:  # deppy: lint-ok[exception-hygiene] — chaos-killed replicas re-shutdown on the cleanup path
                pass


def run_soak(seconds: float = 75.0, rate: float = 25.0,
             seed: int = 1117, n_families: int = 12, bundles: int = 5,
             size: int = 6, sample_every: int = 7,
             max_in_flight: int = 64, p99_budget_ms: float = 2000.0,
             warm_hit_floor: float = 0.8,
             out_path: Optional[str] = None) -> dict:
    from ..telemetry import percentile

    log(f"soak workload: {seconds:.0f}s at {rate}/s open-loop, "
        f"{n_families} Zipf families over a {bundles}x{size} catalog, "
        f"3 replicas + runtime joiner, 2 peered routers, seed {seed}")
    fleet = SoakFleet(seconds, rate, seed, n_families, bundles, size,
                      sample_every, max_in_flight)
    st = fleet.stats
    try:
        t0 = time.monotonic()
        chaos = threading.Thread(target=fleet._chaos, args=(t0,),
                                 name="soak-chaos", daemon=True)
        chaos.start()
        fleet._generate(t0 + seconds)
        chaos.join(timeout=30)
        wall = time.monotonic() - t0
        mismatches = fleet._replay_oracle()
        warm_hit = fleet._warm_hit_post_join()
        lat = sorted(st.latencies)
        p99_ms = round(percentile(lat, 99) * 1e3, 3) if lat else 0.0
        p50_ms = round(percentile(lat, 50) * 1e3, 3) if lat else 0.0
        gates = {
            "client_errors": len(st.errors) == 0,
            "byte_identity": mismatches == 0,
            "gold_sheds": st.sheds.get("gold", 0) == 0,
            "p99_budget": bool(lat) and p99_ms <= p99_budget_ms,
            "warm_hit_post_join": (warm_hit is not None
                                   and warm_hit >= warm_hit_floor),
            "chaos_script_complete": len(fleet.chaos_log) == 4,
        }
        passed = all(gates.values())
        record = {
            "metric": ("soak survival p99 ms (open-loop churn across "
                       "kill/join/drain/router-failover)"),
            "value": p99_ms,
            "unit": "ms",
            "vs_baseline": round(warm_hit, 4) if warm_hit is not None
            else 0.0,
            "workload": "soak",
            "passed": passed,
            "gates": gates,
            "seconds": round(wall, 1),
            "rate": rate,
            "requests_ok": st.ok,
            "errors": st.errors[:20],
            "sheds": st.sheds,
            "failovers": st.failovers,
            "generator_drops": st.generator_drops,
            "oracle_samples": len(st.samples),
            "oracle_mismatches": mismatches,
            "p50_ms": p50_ms,
            "p99_ms": p99_ms,
            "warm_hit_post_join": (round(warm_hit, 4)
                                   if warm_hit is not None else None),
            "chaos_log": fleet.chaos_log,
            "peer_view_at_router_kill": fleet.peer_view,
            "backend": "host",
        }
    finally:
        fleet.shutdown()
    log(f"soak verdict: {'PASS' if passed else 'FAIL'}  "
        f"ok {st.ok}  errors {len(st.errors)}  sheds {st.sheds}  "
        f"p99 {p99_ms}ms  warm-hit(post-join) {warm_hit}  "
        f"mismatches {mismatches}  failovers {st.failovers}")
    for err in st.errors[:10]:
        log(f"  error: {err}")
    if out_path:
        import os
        import platform

        full = {
            "issue": 17,
            "record": "soak_r17",
            "platform": {
                "python": platform.python_version(),
                "machine": platform.machine(),
                "cpus": os.cpu_count(),
                "jax_platforms": (os.environ.get("JAX_PLATFORMS")
                                  or "(default)"),
            },
            "note": ("open-loop Zipf mixed-tenant load over an elastic "
                     "3-replica fleet + runtime joiner behind two "
                     "peered routers; chaos script = replica kill, "
                     "runtime join (warm-state stream + arc flip), "
                     "drain, router kill with client failover.  The "
                     "gate is all-of: zero client-visible errors "
                     "beyond counted bulk admission sheds, sampled "
                     "byte-identity vs a fault-free oracle, zero gold "
                     "sheds, p99 under budget, post-join fleet "
                     "warm-hit ratio over the floor."),
            "result": record,
        }
        d = os.path.dirname(out_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(full, fh, indent=1)
            fh.write("\n")
        log(f"wrote {out_path}")
    return record


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seconds", type=float, default=75.0)
    ap.add_argument("--rate", type=float, default=25.0)
    ap.add_argument("--seed", type=int, default=1117)
    ap.add_argument("--n-families", type=int, default=12)
    ap.add_argument("--p99-budget-ms", type=float, default=2000.0)
    ap.add_argument("--warm-hit-floor", type=float, default=0.8)
    ap.add_argument("--out", default=None,
                    help="write the full artifact JSON here "
                    "(benchmarks/results/soak_r17.json)")
    args = ap.parse_args()
    record = run_soak(seconds=args.seconds, rate=args.rate,
                      seed=args.seed, n_families=args.n_families,
                      p99_budget_ms=args.p99_budget_ms,
                      warm_hit_floor=args.warm_hit_floor,
                      out_path=args.out)
    print(json.dumps(record), flush=True)
    return 0 if record.get("passed") else 1


if __name__ == "__main__":
    raise SystemExit(main())
