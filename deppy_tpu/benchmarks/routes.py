"""Distribution-shift routing benchmark (ISSUE 19): learned vs frozen.

The route-health acceptance workload: a hard-instance mix whose frozen
``portfolio`` row is WRONG for the serving box, replayed through the
scheduler's racing path in four passes over the identical request
stream —

  * **frozen** — the deliberately-bad row (slowest definitive backend
    first, the non-definitive relaxation second, so the k=2 race has
    no fast entrant to rescue it) with an epoch-old provenance stamp,
    route learning off.  This is the throughput a fleet eats today
    when traffic drifts away from what tpu_ab measured.
  * **learned** — the same bad row, route plane armed (``mode=on``):
    staleness flags the class, shadow probes measure the excluded
    fast backend at idle priority, the online registry adopts the
    re-ranked row onto the overlay mid-stream, and the tail of the
    pass serves at recovered speed.
  * **oracle** — the fixed best-first row with fresh provenance; the
    upper bound the learner is graded against.
  * **observe/unshifted** — the oracle row plus an ``observe``-mode
    plane: nothing is stale, so the sampler must never fire and the
    plane's overhead on a healthy fleet mix stays ≤ 5%.

Three of every four waves carry one UNSAT lane so the gradient
relaxation can never finish those definitively — exactly the mix
shape that makes a wrong frozen order expensive (the race's other
entrant is the slow serial host); the SAT-only waves land before
adoption can fire, so the relaxation beats the frozen head there and
the ledger charges real regret to the default.  All four passes must
answer byte-identically; throughputs come from the post-warmup
measured segment, the regret/stale/shadow columns from the learned
pass's plane snapshot — the same numbers ``deppy routes`` rebuilds
offline.

Emits one JSON record in the bench.py contract: ``value`` the learned
pass's steady-state resolutions/sec, ``vs_baseline`` the
learned-to-frozen recovery ratio (the >= 2x acceptance), plus
``oracle_ratio`` (>= 0.8), ``shadow_overhead_ratio`` (<= 1.05) and
the route-health columns for BENCH_r19.json.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from .harness import log

STALE_TS = 1000.0  # 1970 — older than any plausible max-age
RACEABLE = ("device", "host", "grad_relax")


def _wave_vars(depth: int, lanes: int, tag: str,
               unsat: bool = True) -> list:
    """One submit()'s worth of chain problems — all the same depth so
    the whole wave coalesces into a single size class.  ``unsat`` makes
    the last lane an UNSAT chain (prohibited tail): the relaxation
    entrant can never answer that flush definitively, so a frozen row
    that excludes the fast exact backend pays the full serial-host
    wall.  SAT-only waves let the relaxation WIN against the frozen
    default — the races that accrue regret."""
    from .. import sat

    wave = []
    for lane in range(lanes):
        t = f"{tag}l{lane}"
        vs = [sat.variable(f"{t}n0", sat.mandatory(),
                           sat.dependency(f"{t}n1"))]
        vs += [sat.variable(f"{t}n{i}", sat.dependency(f"{t}n{i + 1}"))
               for i in range(1, depth - 1)]
        if unsat and lane == lanes - 1:
            vs.append(sat.variable(f"{t}n{depth - 1}", sat.prohibited()))
        else:
            vs.append(sat.variable(f"{t}n{depth - 1}"))
        wave.append(vs)
    return wave


def _warm_backends(depth: int, lanes: int) -> None:
    """Re-warm every raceable backend's jit compile on a wave-shaped
    batch.  Each registry rewrite calls ``reload_measured_defaults``,
    which clears the jit caches — without this, the first device
    shadow probe of a pass measures the COMPILE (seconds, not
    milliseconds) and poisons the ledger's estimate."""
    from ..engine import registry as engine_registry
    from ..sat.encode import encode

    probs = [encode(vs) for vs in _wave_vars(depth, lanes, "warmb")]
    for name in RACEABLE:
        try:
            engine_registry.solve_via(name, probs)
        # deppy: lint-ok[exception-hygiene] warm-up only — a backend that cannot serve is simply skipped
        except Exception:
            pass


def _probe(depth: int, lanes: int) -> Dict[str, dict]:
    """Time each raceable backend on one wave-shaped batch (warm call
    first so jit compiles never pollute the measurement) and record
    whether it answers DEFINITIVELY — the racer's winner rule."""
    from ..engine import registry as engine_registry
    from ..sat.encode import encode

    probs = [encode(vs) for vs in _wave_vars(depth, lanes, "probe")]
    verdicts: Dict[str, dict] = {}
    for name in RACEABLE:
        try:
            engine_registry.solve_via(name, probs)  # warm / compile
            t0 = time.perf_counter()
            out = engine_registry.solve_via(name, probs)
            wall = time.perf_counter() - t0
        # deppy: lint-ok[exception-hygiene] a backend that cannot serve the probe is simply not raceable on this box
        except Exception:
            continue
        definitive = (out is not None
                      and all(r is not None and not r.degraded
                              for r in out))
        verdicts[name] = {"wall_s": round(wall, 6),
                          "definitive": definitive}
    return verdicts


def _rows(verdicts: Dict[str, dict]) -> Tuple[str, str]:
    """(frozen, oracle) portfolio rows from the probe verdicts.  Frozen
    leads with the slowest definitive backend and slots every
    non-definitive backend second — the worst top-2 the racer can be
    handed.  Oracle is simply definitive backends fastest-first."""
    definitive = sorted((n for n, v in verdicts.items()
                         if v["definitive"]),
                        key=lambda n: verdicts[n]["wall_s"])
    nondef = sorted((n for n, v in verdicts.items()
                     if not v["definitive"]),
                    key=lambda n: verdicts[n]["wall_s"])
    if len(definitive) < 2:
        raise RuntimeError(
            f"need >= 2 definitive raceable backends, got {definitive}")
    frozen = [definitive[-1]] + nondef + definitive[:-1]
    oracle = definitive + nondef
    return ",".join(frozen), ",".join(oracle)


def _serve(sched, waves: List[list], render) -> Tuple[List[float], list]:
    walls: List[float] = []
    rendered: list = []
    for wave in waves:
        t0 = time.perf_counter()
        results = sched.submit(wave)
        walls.append(time.perf_counter() - t0)
        rendered.extend(render(r) for r in results)
    return walls, rendered


def _freeze(reg_path: str, platform: str, row: str, stale: bool) -> None:
    from ..engine import core as engine_core
    from ..engine import defaults_store

    try:
        os.unlink(reg_path)
    except OSError:
        pass
    evidence: dict = {"platform": platform, "samples": 4}
    if stale:
        evidence["ts"] = STALE_TS
    defaults_store.merge_rows(platform, {"portfolio": row},
                              evidence=evidence, path=reg_path)
    engine_core.reload_measured_defaults()


def run(depth: int = 40, lanes: int = 6, warm_waves: int = 8,
        meas_waves: int = 12, shadow_rate: float = 0.5,
        out_path: Optional[str] = None) -> dict:
    import jax

    from .. import io as problem_io
    from .. import routes, telemetry
    from ..engine import core as engine_core
    from ..engine import registry as engine_registry
    from ..sched import scheduler as sched_mod
    from ..sched.scheduler import Scheduler

    platform = jax.default_backend()
    reg_path = tempfile.mktemp(prefix="routes_bench_reg_",
                               suffix=".json")
    prev_env = os.environ.get("DEPPY_TPU_MEASURED_DEFAULTS")
    prev_path = engine_core._MEASURED_DEFAULTS_PATH
    os.environ["DEPPY_TPU_MEASURED_DEFAULTS"] = reg_path
    engine_core._MEASURED_DEFAULTS_PATH = reg_path
    engine_core.reload_measured_defaults()

    n_waves = warm_waves + meas_waves
    # Every 4th wave (starting at wave 1, BEFORE the learner can have
    # adopted) is SAT-only: the relaxation entrant finishes
    # definitively there and BEATS the frozen serial-host head — the
    # races that charge regret to the default.
    waves = [_wave_vars(depth, lanes, f"w{i}", unsat=(i % 4 != 1))
             for i in range(n_waves)]
    render = problem_io.result_to_dict

    def sched_kw():
        return dict(backend="auto", portfolio="on", cache_size=0,
                    incremental="off")

    def measured_wall(walls: List[float]) -> float:
        return sum(walls[warm_waves:])

    try:
        verdicts = _probe(depth, lanes)
        frozen_row, oracle_row = _rows(verdicts)
        log(f"probe: {verdicts}")
        log(f"frozen row: {frozen_row}  oracle row: {oracle_row}")

        # ---- pass 1: frozen stale row, no plane ---------------------
        _freeze(reg_path, platform, frozen_row, stale=True)
        _warm_backends(depth, lanes)
        sched = Scheduler(**sched_kw())
        sched.start()
        frozen_walls, frozen_res = _serve(sched, waves, render)
        sched.stop()
        sched_mod._join_race_threads()
        log(f"frozen pass: {measured_wall(frozen_walls):.3f}s measured")

        # ---- pass 2: same stale row, route plane learning -----------
        _freeze(reg_path, platform, frozen_row, stale=True)
        _warm_backends(depth, lanes)
        sched = Scheduler(**sched_kw())
        sched.start()
        plane = routes.start_plane(sched, mode="on",
                                   shadow_rate=shadow_rate,
                                   min_samples=2)
        adoption_wave = None
        stale_peak = 0
        learned_walls: List[float] = []
        learned_res: list = []
        for i, wave in enumerate(waves):
            t0 = time.perf_counter()
            results = sched.submit(wave)
            learned_walls.append(time.perf_counter() - t0)
            learned_res.extend(render(r) for r in results)
            if plane is not None:
                # Adoption marks the class fresh, so the END-of-pass
                # gauge reads 0 on success; the column reports the peak.
                stale_peak = max(stale_peak,
                                 plane.watcher.stale_count())
            if adoption_wave is None and engine_registry.route_overlay():
                adoption_wave = i
        snap = plane.snapshot() if plane is not None else {}
        routes.stop_plane()
        sched.stop()
        sched_mod._join_race_threads()
        regret_s = sum(s for c in (snap.get("classes") or {}).values()
                       for s in (c.get("regret_s") or {}).values())
        shadow_n = sum(v.get("dispatches", 0)
                       for v in (snap.get("shadow") or {}).values())
        stale_n = stale_peak
        log(f"learned pass: {measured_wall(learned_walls):.3f}s "
            f"measured, adopted at wave {adoption_wave}, "
            f"regret {regret_s:.3f}s, {shadow_n} shadow probes")

        # ---- pass 3: oracle best-first row, no plane ----------------
        _freeze(reg_path, platform, oracle_row, stale=False)
        _warm_backends(depth, lanes)
        sched = Scheduler(**sched_kw())
        sched.start()
        # Two rounds, min measured wall: the oracle/observe comparison
        # resolves a <= 5% delta, far below single-round noise on a
        # loaded CI box.
        oracle_walls, oracle_res = _serve(sched, waves, render)
        oracle_walls2, _ = _serve(sched, waves, render)
        sched.stop()
        sched_mod._join_race_threads()
        oracle_wall = min(measured_wall(oracle_walls),
                          measured_wall(oracle_walls2))
        log(f"oracle pass: {oracle_wall:.3f}s measured (min of 2)")

        # ---- pass 4: unshifted mix + observe plane ------------------
        _freeze(reg_path, platform, oracle_row, stale=False)
        _warm_backends(depth, lanes)
        sched = Scheduler(**sched_kw())
        sched.start()
        plane = routes.start_plane(sched, mode="observe",
                                   shadow_rate=shadow_rate)
        obs_walls, obs_res = _serve(sched, waves, render)
        obs_walls2, _ = _serve(sched, waves, render)
        obs_snap = plane.snapshot() if plane is not None else {}
        routes.stop_plane()
        sched.stop()
        sched_mod._join_race_threads()
        obs_wall = min(measured_wall(obs_walls),
                       measured_wall(obs_walls2))
        obs_shadow = sum(v.get("dispatches", 0)
                         for v in (obs_snap.get("shadow") or {}).values())
        log(f"observe pass: {obs_wall:.3f}s measured (min of 2), "
            f"{obs_shadow} shadow probes on the unshifted mix")
    finally:
        if prev_env is None:
            os.environ.pop("DEPPY_TPU_MEASURED_DEFAULTS", None)
        else:
            os.environ["DEPPY_TPU_MEASURED_DEFAULTS"] = prev_env
        engine_core._MEASURED_DEFAULTS_PATH = prev_path
        engine_core.reload_measured_defaults()
        engine_registry.set_route_overlay({})
        for path in (reg_path, reg_path + ".lock"):
            try:
                os.unlink(path)
            except OSError:
                pass

    n_meas = meas_waves * lanes
    frozen_wall = measured_wall(frozen_walls)
    learned_wall = measured_wall(learned_walls)
    learned_rate = n_meas / learned_wall if learned_wall else 0.0
    frozen_rate = n_meas / frozen_wall if frozen_wall else 0.0
    oracle_rate = n_meas / oracle_wall if oracle_wall else 0.0
    identical = (frozen_res == learned_res == oracle_res == obs_res)
    record = {
        "metric": ("distribution-shift resolutions/sec "
                   "(learned routing vs frozen stale default)"),
        "value": round(learned_rate, 1),
        "unit": "problems/s",
        "vs_baseline": (round(learned_rate / frozen_rate, 3)
                        if frozen_rate else 0.0),
        "workload": "routes",
        "n_problems": n_meas,
        "depth": depth,
        "lanes_per_wave": lanes,
        "waves": {"warm": warm_waves, "measured": meas_waves},
        "probe": verdicts,
        "frozen_row": frozen_row,
        "oracle_row": oracle_row,
        "frozen_rate": round(frozen_rate, 1),
        "oracle_rate": round(oracle_rate, 1),
        "oracle_ratio": (round(learned_rate / oracle_rate, 3)
                         if oracle_rate else 0.0),
        "adoption_wave": adoption_wave,
        "identical": identical,
        "shadow_overhead_ratio": (round(obs_wall / oracle_wall, 3)
                                  if oracle_wall else 0.0),
        "unshifted_shadow_dispatches": obs_shadow,
        # The BENCH_r19 route-health columns: regret the learned pass
        # charged to the frozen default, as seconds and as a fraction
        # of the pass's full serving wall.
        "route_regret_s": round(regret_s, 4),
        "route_regret_ratio": (round(regret_s / sum(learned_walls), 4)
                               if sum(learned_walls) else 0.0),
        "stale_classes": stale_n,
        "shadow_dispatches": shadow_n,
        "backend": platform,
    }
    if out_path:
        import platform as platform_mod

        full = {
            "issue": 19,
            "record": "routes_r19",
            "platform": {
                "python": platform_mod.python_version(),
                "machine": platform_mod.machine(),
                "cpus": os.cpu_count(),
                "jax_platforms": (os.environ.get("JAX_PLATFORMS")
                                  or "(default)"),
            },
            "note": ("distribution-shift routing A/B through the "
                     "scheduler racing path; every wave carries one "
                     "UNSAT lane so the relaxation entrant can never "
                     "finish definitively and a wrong frozen top-2 "
                     "costs the full serial-host wall; frozen/learned/"
                     "oracle/observe passes serve the identical "
                     "request stream and must answer byte-identically; "
                     "throughputs from the post-warmup segment"),
            **record,
        }
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(full, fh, indent=1)
            fh.write("\n")
        log(f"wrote {out_path}")
    return record


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--depth", type=int, default=40)
    ap.add_argument("--lanes", type=int, default=6)
    ap.add_argument("--warm-waves", type=int, default=8)
    ap.add_argument("--meas-waves", type=int, default=12)
    ap.add_argument("--shadow-rate", type=float, default=0.5)
    ap.add_argument("--out", default=None,
                    help="also write the full record (the benchmarks/"
                    "results/routes_r19.json artifact)")
    args = ap.parse_args()
    record = run(depth=args.depth, lanes=args.lanes,
                 warm_waves=args.warm_waves, meas_waves=args.meas_waves,
                 shadow_rate=args.shadow_rate, out_path=args.out)
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
