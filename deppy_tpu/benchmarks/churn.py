"""Churn-replay benchmark (ISSUE 10): warm-started vs cold re-resolution.

The dominant production access pattern is re-resolution: a catalog
changes ONE bundle's constraints and every dependent client re-asks a
99%-identical problem.  This workload replays that traffic shape — a
bundle catalog where each consecutive request flips exactly one
dependency clause (one changed clause out of hundreds) — twice through
the library serving path: once with the delta-aware incremental tier
(clause-set index + warm starts), once cold-only.  Both passes pay the
full request cost (encode, canonical fingerprint, solve), so the
reported speedup is end-to-end, not solve-only.

Emits one JSON record on stdout in the bench.py contract
(``metric``/``value``/``unit``/``vs_baseline``), with ``value`` the
warm-tier throughput, ``vs_baseline`` the warm/cold speedup (the ≥3×
acceptance), and ``incremental_hit_ratio`` / ``warm_fallbacks``
recording how much of the replay was actually served warm.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import List

import numpy as np

from .harness import log


def churn_requests(n_requests: int, n_bundles: int,
                   bundle_size: int, variants: int = 3) -> List[list]:
    """The replay: request ``i`` rotates bundle ``i % n_bundles``'s
    mid-chain dependency to its next candidate pair — consecutive
    requests differ by exactly one clause (one row removed, its
    replacement added: a ``mixed`` delta whose cone is one bundle)."""
    from .. import sat

    def catalog(state):
        vs = []
        for b in range(n_bundles):
            for j in range(bundle_size):
                cons = []
                if j == 0:
                    cons.append(sat.mandatory())
                if j < bundle_size - 2:
                    off = state[b] if j == 2 else 0
                    c1 = (j + 1 + off) % bundle_size or 1
                    c2 = (j + 2 + off) % bundle_size or 2
                    if c1 <= j:
                        c1 = j + 1
                    if c2 <= j:
                        c2 = min(j + 2, bundle_size - 1)
                    cons.append(sat.dependency(f"b{b}v{c1}",
                                               f"b{b}v{c2}"))
                vs.append(sat.variable(f"b{b}v{j}", *cons))
        return vs

    state = [0] * n_bundles
    out = []
    for i in range(n_requests):
        state[i % n_bundles] = (state[i % n_bundles] + 1) % variants
        out.append(catalog(list(state)))
    return out


def replay(requests: List[list], warm: bool) -> dict:
    """One full pass over the replay.  ``warm=True`` consults/feeds a
    ClauseSetIndex exactly like the scheduler's incremental lane class
    (plan → warm attempt → cold fallback); ``warm=False`` is the
    pre-tier serving path.  Every request pays encode + canonical
    fingerprint either way."""
    from ..incremental import ClauseSetIndex
    from ..sat.encode import encode
    from ..sat.errors import Incomplete
    from ..sat.host import HostEngine, WarmStartConflict
    from ..sched.cache import fingerprint

    index = ClauseSetIndex() if warm else None
    served = fallbacks = 0
    t0 = time.perf_counter()
    for vs in requests:
        problem = encode(vs)
        key = fingerprint(problem)
        result = None
        index_steps = None
        if index is not None:
            plan = index.plan(problem, key, 1 << 24)
            if plan is not None:
                eng = HostEngine(problem)
                try:
                    _, idx = eng.solve_warm(plan.warm_assign, plan.cone)
                    result = (idx, eng)
                    served += 1
                    index.note_served()
                    # Index under a cold-equivalent cost (the scheduler
                    # convention): the warm attempt's own count would
                    # erode the budget gate.
                    index_steps = plan.entry_steps + eng.steps
                except (WarmStartConflict, Incomplete):
                    fallbacks += 1
                    index.note_fallback()
        if result is None:
            eng = HostEngine(problem)
            _, idx = eng.solve()
            result = (idx, eng)
        if index is not None:
            idx, eng = result
            model = np.zeros(problem.n_vars, dtype=bool)
            model[list(idx)] = True
            index.store(key, problem, model,
                        index_steps if index_steps is not None
                        else eng.steps,
                        eng.backtracks)
    wall = time.perf_counter() - t0
    return {
        "rate": len(requests) / wall,
        "wall_s": round(wall, 3),
        "served": served,
        "fallbacks": fallbacks,
        "hit_ratio": index.hit_ratio() if index is not None else 0.0,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-requests", type=int, default=120)
    ap.add_argument("--bundles", type=int, default=32)
    ap.add_argument("--bundle-size", type=int, default=12)
    args = ap.parse_args(argv)

    requests = churn_requests(args.n_requests, args.bundles,
                              args.bundle_size)
    from ..sat.encode import encode

    p0 = encode(requests[0])
    n_clauses = int(p0.clauses.shape[0])
    log(f"churn replay: {args.n_requests} requests, {n_clauses} clauses, "
        f"{p0.n_vars} vars, 1 clause changed per request")

    cold = replay(requests, warm=False)
    log(f"cold: {cold['rate']:.1f}/s ({cold['wall_s']}s)")
    warm = replay(requests, warm=True)
    log(f"warm: {warm['rate']:.1f}/s ({warm['wall_s']}s), "
        f"{warm['served']} served, {warm['fallbacks']} fallbacks, "
        f"hit ratio {warm['hit_ratio']}")

    record = {
        "metric": "churn-replay resolutions/sec (warm-start vs cold)",
        "value": round(warm["rate"], 1),
        "unit": "problems/s",
        "vs_baseline": round(warm["rate"] / max(cold["rate"], 1e-9), 2),
        "workload": "churn",
        "n_requests": args.n_requests,
        "n_clauses": n_clauses,
        "n_vars": int(p0.n_vars),
        "cold_rate": round(cold["rate"], 1),
        "warm_rate": round(warm["rate"], 1),
        "incremental_hit_ratio": warm["hit_ratio"],
        "warm_served": warm["served"],
        "warm_fallbacks": warm["fallbacks"],
        "backend": "host",
    }
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
