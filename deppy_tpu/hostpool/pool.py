"""The forkserver-backed host-engine worker pool (ISSUE 5 tentpole).

One :class:`HostPool` per process (``default_pool``), started lazily on
the first dispatch.  The parent keeps one duplex pipe per worker and
multiplexes results and worker deaths with ``multiprocessing.connection.
wait``, which buys the fault vocabulary the serial loop never had:

  * a worker that dies mid-solve is detected by its process sentinel,
    its lane retried on a fresh worker (``deppy_fault_retries`` charged,
    ``deppy_hostpool_worker_crashes_total`` counted) up to the fault
    policy's attempt budget, then solved inline — answers survive any
    crash;
  * workers recycle after ``DEPPY_TPU_HOST_WORKER_RECYCLE`` solves
    (leak hygiene for a service that host-serves for hours while the
    breaker is open);
  * per-lane deadlines cancel only the expired lane: queued lanes are
    triaged at assignment (and again worker-side just before the solve),
    so one stale request never degrades its pool batchmates;
  * a fork-restricted sandbox (or any spawn failure) marks the pool
    unavailable and every consumer falls back to the inline engine —
    byte-identically, because the fallback runs the same
    :func:`~deppy_tpu.hostpool.worker.solve_lane` the workers run.

Dispatches are serialized by one pool lock (host-path consumers are the
scheduler's single drain loop and the driver's recovery wrapper — not a
contention surface) and run under a ``hostpool.dispatch`` span; each
lane's worker-side wall clock comes back in its result and is recorded
as a ``hostpool.worker_solve`` span on the submitting thread, so the
pool time grafts into the submitting request's trace record
(``deppy trace ID`` / ``deppy stats --span hostpool.dispatch``).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import List, Optional, Sequence, Union

from .. import config, faults, telemetry
from . import metrics
from .worker import HostLaneResult, _degraded_result, solve_lane, worker_main

# Worker-count policy (ISSUE 5): DEPPY_TPU_HOST_WORKERS / --host-workers,
# default min(cpu_count, 8).  0 disables the pool outright.  An
# UNCONFIGURED default of 1 (single-core box) also disables it: a
# one-worker pool is pure IPC overhead there — but an EXPLICIT 1 is
# honored (the bench baseline's 1-vs-N comparison isolates exactly that
# overhead).
DEFAULT_MAX_WORKERS = 8
# Workers retire after this many solves and are replaced (0 = never).
DEFAULT_RECYCLE_AFTER = 256
# Bound on waiting for a spawned worker's ready handshake; a sandbox
# that allows fork but hangs it must not hang the solve path.
DEFAULT_SPAWN_TIMEOUT_S = 30.0


class HostPoolError(RuntimeError):
    """Pool infrastructure failure (spawn refused, workers gone).

    Never a solve verdict: consumers catch it and fall back to the
    inline engine, byte-identically.  Semantic outcomes
    (``InternalSolverError`` from a malformed problem) propagate
    through the pool untouched."""


def _env_int(name: str, default: int) -> int:
    v = faults.env_float(name, float(default), warn=True)
    return int(v if v is not None else default)


def pool_workers() -> int:
    """The configured worker count: explicit override
    (:func:`configure_pool`), else ``DEPPY_TPU_HOST_WORKERS``, else
    ``min(cpu_count, 8)``."""
    if _OVERRIDE_WORKERS is not None:
        return _OVERRIDE_WORKERS
    raw = config.env_raw("DEPPY_TPU_HOST_WORKERS")
    if raw is not None and raw.strip():
        return max(_env_int("DEPPY_TPU_HOST_WORKERS", 0), 0)
    return min(os.cpu_count() or 1, DEFAULT_MAX_WORKERS)


def _workers_explicit() -> bool:
    if _OVERRIDE_WORKERS is not None:
        return True
    raw = config.env_raw("DEPPY_TPU_HOST_WORKERS")
    return raw is not None and bool(raw.strip())


def effective_workers() -> int:
    """Workers the host path will actually use: 0 = inline serial
    engine (pool disabled or not engaged).  The bench harness records
    this as the ``host_workers`` column so every BENCH row states which
    host-path configuration it measured."""
    n = pool_workers()
    if n < 1 or (n < 2 and not _workers_explicit()):
        return 0
    return n


class _Worker:
    __slots__ = ("proc", "conn", "solves", "busy_seqs", "wid")

    def __init__(self, proc, conn, wid: int):
        self.proc = proc
        self.conn = conn
        self.solves = 0
        # In-flight chunk seqs, FIFO.  Up to _PIPELINE_DEPTH chunks are
        # outstanding per worker so the pipe buffer hides the parent's
        # serialization latency: with one chunk in flight the worker
        # idles for the whole recv→process→pickle→send gap between
        # chunks (measured ~17% of a chunk's wall on the config-2
        # batch; the 1-worker pool ran at 0.6x inline because of it).
        self.busy_seqs: deque = deque()
        self.wid = wid


# Outstanding chunks per worker (2 = double buffering: one solving, one
# queued in the pipe).  More buys nothing and worsens crash-retry and
# deadline-triage granularity.
_PIPELINE_DEPTH = 2


class HostPool:
    """A pool of host-engine worker processes solving lanes concurrently."""

    def __init__(self, workers: Optional[int] = None,
                 recycle_after: Optional[int] = None,
                 spawn_timeout_s: Optional[float] = None,
                 start_method: Optional[str] = None):
        self.workers = workers if workers is not None else pool_workers()
        if recycle_after is None:
            recycle_after = _env_int("DEPPY_TPU_HOST_WORKER_RECYCLE",
                                     DEFAULT_RECYCLE_AFTER)
        self.recycle_after = max(int(recycle_after), 0)
        if spawn_timeout_s is None:
            spawn_timeout_s = faults.env_float(
                "DEPPY_TPU_HOSTPOOL_SPAWN_TIMEOUT_S",
                DEFAULT_SPAWN_TIMEOUT_S, warn=True)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.start_method = (start_method
                             or config.env_raw(
                                 "DEPPY_TPU_HOSTPOOL_START_METHOD")
                             or "forkserver")
        from ..analysis import lockdep

        # One lock serializes dispatches AND lifecycle; a dispatch in
        # flight therefore drains before shutdown proceeds.
        self._lock = lockdep.make_lock("hostpool.pool")
        self._ctx = None
        self._workers: List[_Worker] = []
        self._next_wid = 0
        self._unavailable: Optional[str] = None  # sticky failure reason
        self._started = False
        self._shutdown = False
        self._last_crashes = 0
        # Pool-lifetime monotonic task counter.  Never per-dispatch: an
        # engine error escaping a dispatch (fail-loud InternalSolverError
        # re-raised from an inline re-solve) leaves pipelined chunks in
        # flight, and a per-dispatch counter restarting at 0 would let
        # the NEXT dispatch adopt those stale results as its own lanes'
        # answers.  With a monotonic seq, a stale message resolves to no
        # chunk and is dropped.
        self._seq = 0

    # ------------------------------------------------------------ lifecycle

    def _ensure_started_locked(self) -> None:
        if self._shutdown:
            raise HostPoolError("host pool is shut down")
        if self._unavailable is not None:
            raise HostPoolError(
                f"host pool unavailable: {self._unavailable}")
        if self._started:
            if not self._workers:
                raise HostPoolError("host pool has no live workers")
            return
        if self.workers < 1:
            self._unavailable = "configured with zero workers"
            raise HostPoolError(self._unavailable)
        try:
            import multiprocessing as mp

            self._ctx = mp.get_context(self.start_method)
            if self.start_method == "forkserver":
                try:
                    # Preload the worker module (numpy + the sat layer)
                    # into the forkserver so every forked worker starts
                    # warm instead of re-importing per process.
                    self._ctx.set_forkserver_preload(
                        ["deppy_tpu.hostpool.worker"])
                except (ValueError, RuntimeError):
                    pass  # forkserver already running: keep its state
            for _ in range(self.workers):
                self._workers.append(self._spawn_locked())
        except HostPoolError:
            self._teardown_locked()
            raise
        except Exception as e:  # fork-restricted sandbox, missing ctx, ...
            self._teardown_locked()
            self._unavailable = f"{type(e).__name__}: {e}"
            raise HostPoolError(
                f"host pool unavailable: {self._unavailable}") from e
        self._started = True
        metrics.gauge("deppy_hostpool_workers").set(len(self._workers))

    def _spawn_locked(self) -> _Worker:
        """Start one worker and wait for its ready handshake."""
        import sys

        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        wid = self._next_wid
        self._next_wid += 1
        proc = self._ctx.Process(
            target=worker_main, args=(child_conn, wid),
            name=f"deppy-hostpool-{wid}", daemon=True)
        # Script-less interpreters (``python - <<EOF``, some REPL
        # embeddings) carry a ``__main__.__file__`` of "<stdin>"; the
        # forkserver's child prep re-runs that path and dies before the
        # ready handshake.  The worker never needs the caller's main
        # module — strip the phantom path for the instant the prep data
        # is captured so heredoc-driven library use still gets a pool.
        main = sys.modules.get("__main__")
        main_file = getattr(main, "__file__", None)
        strip = (main_file is not None
                 and getattr(main, "__spec__", None) is None
                 and not os.path.exists(main_file))
        if strip:
            del main.__file__
        try:
            proc.start()
        finally:
            if strip:
                main.__file__ = main_file
        child_conn.close()
        if not parent_conn.poll(self.spawn_timeout_s):
            proc.terminate()
            proc.join(5)
            parent_conn.close()
            raise HostPoolError(
                f"worker {wid} never reported ready within "
                f"{self.spawn_timeout_s}s")
        msg = parent_conn.recv()
        if msg[0] != "ready":
            proc.terminate()
            proc.join(5)
            parent_conn.close()
            raise HostPoolError(
                f"worker {wid} bad handshake: {msg!r}")
        return _Worker(proc, parent_conn, wid)

    def _retire_locked(self, w: _Worker, graceful: bool) -> None:
        if graceful:
            try:
                w.conn.send(("exit",))
            except (OSError, ValueError):
                pass
        try:
            w.conn.close()
        except OSError:
            pass
        w.proc.join(5 if graceful else 1)
        if w.proc.is_alive():
            w.proc.terminate()
            w.proc.join(5)

    def _teardown_locked(self) -> None:
        for w in self._workers:
            self._retire_locked(w, graceful=False)
        self._workers = []
        metrics.gauge("deppy_hostpool_workers").set(0)

    @property
    def running(self) -> bool:
        # Consistent triple under the pool lock (ISSUE 7 concurrency-
        # discipline); never called while holding it — the in-class
        # consumers are the *_locked* helpers, which read the fields
        # directly.
        with self._lock:
            return (self._started and not self._shutdown
                    and bool(self._workers))

    @property
    def available(self) -> bool:
        with self._lock:
            return self._unavailable is None and not self._shutdown

    def worker_pids(self) -> List[int]:
        with self._lock:
            return [w.proc.pid for w in self._workers]

    def shutdown(self) -> None:
        """Drain (the lock serializes against any in-flight dispatch),
        then exit every worker; stragglers are terminated.  Idempotent;
        the pool refuses further dispatches afterwards."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            for w in self._workers:
                self._retire_locked(w, graceful=True)
            self._workers = []
            if self._started:
                metrics.gauge("deppy_hostpool_workers").set(0)

    # -------------------------------------------------------------- solving

    def solve(self, problems: Sequence,
              max_steps: Union[int, Sequence[Optional[int]], None] = None,
              deadlines: Optional[Sequence] = None) -> List[HostLaneResult]:
        """Solve independent lanes concurrently across the workers.

        Raises :class:`HostPoolError` (pool infrastructure) for the
        caller's inline fallback; ``InternalSolverError`` and friends
        from the engine itself propagate typed (a crashed-retry-exhausted
        or engine-errored lane is re-solved inline in THIS process, so
        the real exception surfaces exactly as the serial loop's would).
        """
        faults.inject("hostpool.dispatch")
        n = len(problems)
        per_lane_steps = (list(max_steps) if isinstance(max_steps, (list,
                                                                    tuple))
                          else [max_steps] * n)
        dls = list(deadlines) if deadlines is not None else [None] * n
        with self._lock:
            self._ensure_started_locked()
            reg = telemetry.default_registry()
            metrics.counter("deppy_hostpool_dispatches_total").inc()
            with reg.span("hostpool.dispatch", lanes=n,
                          workers=len(self._workers)) as sp:
                try:
                    results = self._solve_locked(problems, per_lane_steps,
                                                 dls, reg)
                finally:
                    # An escaping engine error (fail-loud path) may
                    # leave pipelined chunks in flight — their stale
                    # results drop by seq on the next dispatch, but the
                    # gauges must read idle between dispatches.
                    metrics.gauge("deppy_hostpool_queue_depth").set(0)
                    metrics.gauge("deppy_hostpool_busy_workers").set(0)
                sp.set(crashes=self._last_crashes)
            return results

    def _solve_locked(self, problems, per_lane_steps, dls, reg):
        from multiprocessing.connection import wait as mp_wait

        n = len(problems)
        results: List[Optional[HostLaneResult]] = [None] * n
        attempts = [0] * n
        max_attempts = max(faults.RetryPolicy.from_env().max_attempts, 1)
        # Tasks are CHUNKS of lanes: per-lane tasks measured slower than
        # the serial loop (the pipe round trip ate the concurrency on
        # ~ms solves).  Oversubscribe 4 chunks per worker so stragglers
        # rebalance while the round trip amortizes over several lanes.
        chunk = max(1, -(-n // (max(len(self._workers), 1) * 4)))
        pending = deque([list(range(lo, min(lo + chunk, n)))
                         for lo in range(0, n, chunk)])
        seq_to_chunk = {}
        self._last_crashes = 0
        g_depth = metrics.gauge("deppy_hostpool_queue_depth")
        g_busy = metrics.gauge("deppy_hostpool_busy_workers")
        h_solve = metrics.histogram("deppy_hostpool_worker_solve_seconds")
        c_lanes = metrics.counter("deppy_hostpool_lanes_total")

        def busy():
            return [w for w in self._workers if w.busy_seqs]

        def finish_inline(i):
            # Last line: this process IS the inline engine, so answers
            # (and loud, typed engine errors) survive any pool failure.
            results[i] = solve_lane(problems[i],
                                    max_steps=per_lane_steps[i],
                                    deadline=dls[i])

        def assign():
            while pending:
                open_ws = [w for w in self._workers
                           if len(w.busy_seqs) < _PIPELINE_DEPTH]
                if not open_ws:
                    break
                # Least-loaded first: fill every worker's first slot
                # before any second, so the pipeline never serializes
                # two chunks behind one worker while another sits idle.
                w = min(open_ws, key=lambda x: len(x.busy_seqs))
                lanes = pending.popleft()
                live = []
                for i in lanes:
                    if results[i] is not None:
                        continue
                    if dls[i] is not None and dls[i].expired():
                        # Cancel only THIS lane's future: queued
                        # batchmates keep their worker slots.
                        results[i] = _degraded_result()
                    else:
                        live.append(i)
                if not live:
                    continue
                crash = False
                try:
                    faults.inject("hostpool.worker_crash")
                except faults.InjectedFault:
                    crash = True
                seq = self._seq
                self._seq += 1
                payloads = [{
                    "problem": problems[i],
                    "max_steps": per_lane_steps[i],
                    "deadline_s": (dls[i].remaining()
                                   if dls[i] is not None else None),
                } for i in live]
                try:
                    w.conn.send(("task", seq, payloads, crash))
                except (OSError, ValueError):
                    # Worker died between dispatches: same handling as a
                    # mid-solve crash (the attempt budget still bounds a
                    # worker population that keeps dying on startup).
                    self._on_crash_locked(w, live, pending, attempts,
                                          max_attempts, finish_inline)
                    continue
                w.busy_seqs.append(seq)
                seq_to_chunk[seq] = live
            g_depth.set(sum(len(c) for c in pending))
            g_busy.set(len(busy()))

        assign()
        while any(r is None for r in results):
            if not self._workers:
                # Every worker (and respawn) is gone: the rest solves
                # inline rather than failing answers already promised.
                for i in range(n):
                    if results[i] is None:
                        finish_inline(i)
                break
            if not busy():
                # Lanes remain but nothing is in flight (all pending
                # were degraded, or sends failed): try assigning again;
                # if nothing sticks, drain inline.
                assign()
                if not busy():
                    for i in range(n):
                        if results[i] is None:
                            finish_inline(i)
                    break
                continue
            conns = {w.conn: w for w in busy()}
            # The worker pipe is the authoritative death signal: a dead
            # worker's conn reads EOF, and EOF (unlike the process
            # sentinel, whose forkserver relay can lag or be swallowed
            # by a PID-1-less sandbox) is level-triggered — deferring it
            # would spin the loop.  Sentinels ride along only to wake
            # the wait for pipe-less deaths.
            sentinels = {w.proc.sentinel: w for w in busy()}
            ready = mp_wait(list(conns) + list(sentinels))
            handled = set()
            for r in ready:
                w = conns.get(r, sentinels.get(r))
                if w is None or id(w) in handled:
                    continue
                handled.add(id(w))
                alive = True
                # Drain every queued message first: results may have
                # been sent just before death.
                while w.busy_seqs and w.conn.poll(0):
                    alive = self._on_message_locked(
                        w, results, seq_to_chunk, h_solve, c_lanes, reg,
                        finish_inline)
                    if not alive:
                        break
                if w.busy_seqs and (not alive or not w.proc.is_alive()):
                    lanes = [i for seq in w.busy_seqs
                             for i in seq_to_chunk.pop(seq, [])]
                    self._on_crash_locked(w, lanes, pending, attempts,
                                          max_attempts, finish_inline)
                elif (not w.busy_seqs and w in self._workers
                      and not w.proc.is_alive()):
                    # Died idle (shouldn't happen): just replace it.
                    self._replace_locked(w, count_crash=False)
            assign()
        g_depth.set(0)
        g_busy.set(0)
        return results

    def _on_message_locked(self, w, results, seq_to_chunk, h_solve,
                           c_lanes, reg, finish_inline) -> bool:
        """Process one queued worker message; False means the pipe hit
        EOF (the worker is dead — caller runs the crash path)."""
        try:
            msg = w.conn.recv()
        except (EOFError, OSError):
            return False
        _, seq, out = msg
        lanes = seq_to_chunk.pop(seq, [])
        try:
            w.busy_seqs.remove(seq)
        except ValueError:
            pass
        w.solves += len(lanes)
        for lane, res in zip(lanes, out):
            if results[lane] is not None:
                continue  # stale (solved inline after a crash storm)
            if isinstance(res, HostLaneResult):
                results[lane] = res
                if not res.degraded:
                    c_lanes.inc()
                    h_solve.observe(res.wall_s)
                    # Worker-side timing, recorded on the submitting
                    # thread so the span joins THIS request's trace
                    # (ISSUE 4's record_span contract — the same move
                    # the scheduler's queue-wait span makes).  Gated on
                    # an actual observer: with neither a sink nor an
                    # active trace, a per-lane span is parent CPU taken
                    # straight from the workers (on a 2-core box the
                    # parent IS the pool's bottleneck), and the
                    # histogram above already carries the timing.
                    from ..telemetry import trace as _trace

                    if (reg.sink_path is not None
                            or _trace.current_context() is not None):
                        reg.record_span("hostpool.worker_solve",
                                        res.wall_s, lane=lane,
                                        worker=w.wid)
            else:  # ("err", messages): engine fault — fail loud,
                # typed, by re-raising from an inline re-solve.
                reg.event("fault", fault="hostpool_worker_error",
                          messages=res[1], lane=lane)
                finish_inline(lane)
        # Recycle only between chunks: a retiring worker must not strand
        # a pipelined task still sitting in its pipe.
        if (self.recycle_after and w.solves >= self.recycle_after
                and not w.busy_seqs):
            metrics.counter("deppy_hostpool_worker_recycles_total").inc()
            self._replace_locked(w, count_crash=False)
        return True

    def _on_crash_locked(self, w, lanes, pending, attempts, max_attempts,
                         finish_inline) -> None:
        """One worker died mid-chunk: count it, charge the retry
        counter, respawn a fresh worker, and requeue the chunk's
        unfinished lanes to re-run there (or solve them inline once
        their attempts exhaust)."""
        metrics.counter("deppy_hostpool_worker_crashes_total").inc()
        faults.fault_counter("deppy_fault_retries").inc()
        telemetry.default_registry().event(
            "fault", fault="hostpool_worker_crash", worker=w.wid,
            exitcode=w.proc.exitcode, lanes=len(lanes))
        retry = []
        for lane in lanes:
            attempts[lane] += 1
            if attempts[lane] >= max_attempts:
                finish_inline(lane)
            else:
                retry.append(lane)
        if retry:
            pending.appendleft(retry)
        self._replace_locked(w, count_crash=True)

    def _replace_locked(self, w: _Worker, count_crash: bool) -> None:
        if w in self._workers:
            self._workers.remove(w)
        self._retire_locked(w, graceful=not count_crash)
        if count_crash:
            self._last_crashes += 1
        try:
            self._workers.append(self._spawn_locked())
        except Exception as e:  # any spawn failure, HostPoolError included
            # Respawn refused (sandbox tightened mid-run): shrink; the
            # solve loop drains inline once the pool empties.  Loud on
            # the sink (ISSUE 7 exception-hygiene): a pool silently
            # shrinking to empty is the flight recorder's business.
            telemetry.default_registry().event(
                "fault", fault="hostpool_respawn_failed",
                error=type(e).__name__, workers=len(self._workers))
        metrics.gauge("deppy_hostpool_workers").set(len(self._workers))


# ---------------------------------------------------------------- inline path


def solve_inline(problems: Sequence,
                 max_steps: Union[int, Sequence[Optional[int]], None] = None,
                 deadlines: Optional[Sequence] = None) -> List[HostLaneResult]:
    """The serial reference path: the same :func:`solve_lane` the
    workers run, in-process, in order.  Per-lane deadline triage before
    each solve reproduces the historical "break at expiry, degrade the
    remainder" host-loop semantics exactly (a shared deadline that
    expires mid-batch fails every subsequent lane's triage)."""
    n = len(problems)
    per_lane_steps = (list(max_steps)
                      if isinstance(max_steps, (list, tuple))
                      else [max_steps] * n)
    dls = list(deadlines) if deadlines is not None else [None] * n
    return [solve_lane(p, max_steps=s, deadline=d)
            for p, s, d in zip(problems, per_lane_steps, dls)]


# --------------------------------------------------------------- default pool

_OVERRIDE_WORKERS: Optional[int] = None
_DEFAULT: Optional[HostPool] = None
_DEFAULT_LOCK = threading.Lock()


def configure_pool(workers: Optional[int]) -> None:
    """Install an explicit worker count (``--host-workers``); replaces
    the default pool on next use.  ``None`` restores env/default
    resolution."""
    global _OVERRIDE_WORKERS, _DEFAULT
    with _DEFAULT_LOCK:
        _OVERRIDE_WORKERS = workers
        old, _DEFAULT = _DEFAULT, None
    if old is not None:
        old.shutdown()


def default_pool() -> Optional[HostPool]:
    """The process-wide pool, or ``None`` when pooling is disabled:
    explicitly (``DEPPY_TPU_HOST_WORKERS=0``), or implicitly on a
    single-core box where the unconfigured default of 1 worker would be
    pure IPC overhead (an explicit 1 is honored — the bench baseline's
    1-vs-N row measures exactly that overhead)."""
    global _DEFAULT
    n = effective_workers()
    if n < 1:
        return None
    pool = _DEFAULT
    if pool is not None and pool.workers == n and not pool._shutdown:
        return pool
    stale = None
    with _DEFAULT_LOCK:
        pool = _DEFAULT
        if pool is None or pool.workers != n or pool._shutdown:
            stale = pool
            _DEFAULT = HostPool(workers=n)
            pool = _DEFAULT
    if stale is not None:
        stale.shutdown()
    return pool


def shutdown_default_pool() -> None:
    """Graceful shutdown of the default pool (service drain, atexit)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        pool, _DEFAULT = _DEFAULT, None
    if pool is not None:
        pool.shutdown()


def solve_host_problems(problems: Sequence,
                        max_steps: Union[int, Sequence[Optional[int]],
                                         None] = None,
                        deadlines: Optional[Sequence] = None,
                        pool: Optional[HostPool] = None,
                        ) -> List[HostLaneResult]:
    """THE host-path entry every consumer calls (solver facade, driver
    fault fallback, scheduler breaker-open drain): pool when one is
    available and the batch has parallelism to exploit, inline
    otherwise — bit-identical either way.

    Pool infrastructure failures (fork-restricted sandbox, injected
    ``hostpool.dispatch`` faults, worker exhaustion) degrade to the
    inline engine loudly (``deppy_hostpool_inline_fallback_total`` +
    a ``fault`` sink event), never to an error: the inline engine is the
    actual last line of defense, and ITS faults stay loud and typed."""
    if pool is None:
        pool = default_pool()
    if pool is not None and len(problems) > 1:
        try:
            return pool.solve(problems, max_steps=max_steps,
                              deadlines=deadlines)
        except (HostPoolError, faults.InjectedFault) as e:
            metrics.counter("deppy_hostpool_inline_fallback_total").inc()
            telemetry.default_registry().event(
                "fault", fault="hostpool_inline_fallback",
                error=type(e).__name__, problems=len(problems))
    return solve_inline(problems, max_steps=max_steps, deadlines=deadlines)
