"""Host-pool worker process: the loop, and the one lane-solve routine.

:func:`solve_lane` is the SINGLE implementation of "solve one lane on
the host engine and report the observables" — the pool's worker
processes run it over a pipe, and the parent's inline fallback
(:func:`deppy_tpu.hostpool.pool.solve_inline`) runs the very same
function in-process, so pool-vs-inline bit-identity (models, unsat
cores, step counts — the ISSUE 5 acceptance) holds by construction, not
by parallel maintenance.

The worker imports no accelerator code: :class:`~deppy_tpu.sat.host.
HostEngine` is pure numpy, and the first thing a worker does is pin
``JAX_PLATFORMS=cpu`` through :func:`platform_env.assert_env_platform`
— on this machine a sitecustomize hook imports jax into every fresh
interpreter (the forkserver included) and registers the axon TPU PJRT
plugin, whose discovery-time init hangs for hours when the tunneled
worker is wedged.  The pin limits discovery to CPU, so a wedged
accelerator can never hang worker startup; a jax-free interpreter skips
the pin entirely (nothing to discover).
"""

from __future__ import annotations

import os
import signal
import sys
import time
from typing import List, Optional, Sequence


class HostLaneResult:
    """One lane's host-engine observables, pool- and inline-shaped alike.

    ``outcome`` is ``"sat"`` / ``"unsat"`` / ``"incomplete"``;
    ``installed_idx`` / ``core_idx`` are the installed-variable /
    active-constraint index lists the inline engine's model and
    ``NotSatisfiable`` core decode to (consumers rebuild their own
    vocabulary from the problem's ``variables`` / ``applied`` lists —
    the same objects either path would hand back).  ``degraded`` marks a
    lane whose deadline expired before its solve started (outcome
    ``"incomplete"``, zero steps) — distinct from budget exhaustion,
    which reports the engine's real step count.
    """

    __slots__ = ("outcome", "installed_idx", "core_idx", "steps",
                 "decisions", "propagation_rounds", "backtracks",
                 "wall_s", "degraded")

    def __init__(self, outcome: str, installed_idx: Sequence[int] = (),
                 core_idx: Sequence[int] = (), steps: int = 0,
                 decisions: int = 0, propagation_rounds: int = 0,
                 backtracks: int = 0, wall_s: float = 0.0,
                 degraded: bool = False):
        self.outcome = outcome
        self.installed_idx = list(installed_idx)
        self.core_idx = list(core_idx)
        self.steps = int(steps)
        self.decisions = int(decisions)
        self.propagation_rounds = int(propagation_rounds)
        self.backtracks = int(backtracks)
        self.wall_s = float(wall_s)
        self.degraded = bool(degraded)

    # __slots__ classes need explicit state plumbing only on protocol 1;
    # the default protocol handles slots — this is a plain value object.

    def key(self) -> tuple:
        """Comparable identity tuple (differential tests)."""
        return (self.outcome, tuple(self.installed_idx),
                tuple(self.core_idx), self.steps, self.decisions,
                self.propagation_rounds, self.backtracks, self.degraded)


def _degraded_result() -> HostLaneResult:
    return HostLaneResult("incomplete", degraded=True)


def solve_lane(problem, max_steps: Optional[int] = None,
               deadline=None, cancel=None) -> HostLaneResult:
    """Solve one lowered problem on the host spec engine.

    ``deadline`` is any object with ``expired()`` (``faults.Deadline``
    inline; a worker-local clock over the pipe): expiry before the solve
    starts degrades the lane — admission control, exactly like the
    driver's per-group check — never mid-solve preemption.

    ``cancel`` (inline callers only — events don't cross the worker
    pipe) is the portfolio race's cooperative stop flag: the engine
    checks it at step boundaries and raises
    :class:`~deppy_tpu.sat.host.SolveCancelled`, which propagates (a
    cancelled lane has no answer to report).

    ``InternalSolverError`` (malformed problem, minimization failure)
    propagates: the host engine is the last line of defense and masking
    its faults would return wrong answers (docs/robustness.md).
    """
    from ..sat.errors import Incomplete, NotSatisfiable
    from ..sat.host import HostEngine

    if deadline is not None and deadline.expired():
        return _degraded_result()
    eng = HostEngine(problem, max_steps=max_steps, cancel=cancel)
    t0 = time.perf_counter()
    outcome = "incomplete"
    installed_idx: List[int] = []
    core_idx: List[int] = []
    try:
        _, installed_idx = eng.solve()
        # solve() returns (variables, indices); keep the indices.
        installed_idx = list(installed_idx)
        outcome = "sat"
    except NotSatisfiable as e:
        # solve() already ran the deletion sweep; the exception carries
        # the very objects of problem.applied, so the index list
        # rebuilds by identity — re-running unsat_core_mask would double
        # the step charge and could flip an in-budget UNSAT to
        # Incomplete (the driver fallback's documented pitfall).
        ids = {id(c) for c in e.constraints}
        core_idx = [j for j, c in enumerate(problem.applied)
                    if id(c) in ids]
        outcome = "unsat"
    except Incomplete:
        outcome = "incomplete"
    return HostLaneResult(
        outcome, installed_idx, core_idx, eng.steps, eng.decisions,
        eng.propagation_rounds, eng.backtracks,
        time.perf_counter() - t0,
    )


class _WireDeadline:
    """Deadline reconstructed from remaining-seconds at send time.

    Monotonic clocks don't transfer between processes; the remaining
    budget does.  Pipe latency slightly loosens the budget — the safe
    direction (a lane is never degraded earlier than inline would)."""

    __slots__ = ("_expires",)

    def __init__(self, remaining_s: float):
        self._expires = time.monotonic() + remaining_s

    def expired(self) -> bool:
        return time.monotonic() >= self._expires


# Exit code a worker uses for a scripted crash (the parent's
# ``hostpool.worker_crash`` fault point): distinguishable in logs from a
# real segfault, handled identically by the crash-retry path.
CRASH_EXIT_CODE = 70


def worker_main(conn, worker_id: int) -> None:
    """The worker process body: pin the platform, then serve lane tasks
    off the duplex pipe until told to exit (or the pipe closes).

    Protocol (parent → worker): ``("task", seq, lanes, crash)`` where
    ``lanes`` is a CHUNK — a list of payload dicts with keys ``problem``
    / ``max_steps`` / ``deadline_s`` (remaining seconds or None) — and
    ``crash`` scripts a mid-task death (the ``hostpool.worker_crash``
    fault point); ``("exit",)``.  Chunking amortizes the pipe round trip
    over several ~ms solves (per-lane tasks measured SLOWER than the
    serial loop on the config-2 workload: IPC ate the concurrency).
    Worker → parent: ``("ready", pid)`` once at startup, then
    ``("result", seq, out)`` with one entry per lane — a
    :class:`HostLaneResult`, or ``("err", messages)`` when the engine
    itself failed on that lane (the parent re-solves it inline so the
    real exception surfaces loud and typed).  Deadlines are re-checked
    per lane just before each solve, so an expiry mid-chunk degrades
    only the lanes not yet started."""
    # JAX_PLATFORMS=cpu + assert_env_platform: a wedged accelerator
    # plugin must never hang worker startup (module docstring).  The
    # env pin covers any subprocess a worker might itself spawn; the
    # config pin is only needed when this interpreter already imported
    # jax (sitecustomize) — a jax-free worker has nothing to discover.
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "jax" in sys.modules:
        from ..utils.platform_env import assert_env_platform

        assert_env_platform()
    # The parent owns interrupt handling; a Ctrl-C must drain through
    # the pool's graceful shutdown, not kill workers mid-solve.
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    conn.send(("ready", os.getpid()))
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return  # parent died or closed the pipe: exit quietly
        if msg[0] == "exit":
            return
        _, seq, lanes, crash = msg
        if crash:
            # Scripted worker death (fault injection), mid-task so the
            # parent sees a busy worker's sentinel fire — the exact
            # shape of a real crash.
            os._exit(CRASH_EXIT_CODE)
        out = []
        for payload in lanes:
            deadline = None
            if payload.get("deadline_s") is not None:
                deadline = _WireDeadline(payload["deadline_s"])
            try:
                out.append(solve_lane(payload["problem"],
                                      max_steps=payload.get("max_steps"),
                                      deadline=deadline))
            except Exception as e:  # noqa: BLE001 — parent re-raises inline
                out.append(("err", [f"{type(e).__name__}: {e}"]))
        try:
            conn.send(("result", seq, out))
        except (OSError, ValueError):
            return
