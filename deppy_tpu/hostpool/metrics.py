"""The hostpool metric families — single source of truth.

Same pattern as :mod:`deppy_tpu.faults.metrics`: every family the worker
pool touches is declared here once (name, kind, help) and accessed
through the helpers, so the help text cannot drift between the
incrementing site and the service's ``/metrics`` mirror
(:func:`render_metric_lines`), and docs/observability.md's table has
exactly one thing to stay in sync with.

All families live on :func:`deppy_tpu.telemetry.default_registry` — the
pool is process-global (one host, one pool), like the fault layer's
breaker counters.  The helpers re-fetch the family from the *current*
default registry on every call instead of caching the family object, so
tests that swap the registry (``set_default_registry``) observe pool
activity on their own registry.
"""

from __future__ import annotations

# name -> help, in exposition order.
GAUGES = {
    "deppy_hostpool_queue_depth":
        "Lanes waiting for a host-pool worker right now.",
    "deppy_hostpool_busy_workers":
        "Host-pool workers currently solving a lane.",
    "deppy_hostpool_workers":
        "Host-engine worker processes alive in the pool.",
}

COUNTERS = {
    "deppy_hostpool_dispatches_total":
        "Batches dispatched through the host worker pool.",
    "deppy_hostpool_lanes_total":
        "Lanes solved by host-pool workers.",
    "deppy_hostpool_worker_crashes_total":
        "Host-pool workers that died mid-solve (lane retried on a "
        "fresh worker).",
    "deppy_hostpool_worker_recycles_total":
        "Host-pool workers retired after their solve-count limit and "
        "replaced.",
    "deppy_hostpool_inline_fallback_total":
        "Host-path batches solved by the inline engine because the "
        "pool was unavailable or its dispatch failed.",
}

HISTOGRAMS = {
    "deppy_hostpool_worker_solve_seconds":
        "Worker-side wall clock per pool-solved lane.",
}

FAMILY_ORDER = (*GAUGES, *COUNTERS, *HISTOGRAMS)


def gauge(name: str):
    from .. import telemetry

    return telemetry.default_registry().gauge(name, GAUGES[name])


def counter(name: str):
    from .. import telemetry

    return telemetry.default_registry().counter(name, COUNTERS[name])


def histogram(name: str):
    from .. import telemetry

    return telemetry.default_registry().histogram(name, HISTOGRAMS[name])


def render_metric_lines() -> list:
    """Prometheus exposition lines for every hostpool family, for the
    service's ``Metrics.render`` to append — the same injection pattern
    as ``faults.render_metric_lines``.  Families register at zero on
    first render so a scrape shows the whole table before the pool's
    first dispatch (gauges default to 0 only while unset — a live value
    is never stomped)."""
    from .. import telemetry

    for name in GAUGES:
        g = gauge(name)
        if g.value is None:
            g.set(0)
    for name in COUNTERS:
        counter(name)
    for name in HISTOGRAMS:
        histogram(name)
    return telemetry.default_registry().render_families(list(FAMILY_ORDER))
