"""deppy_tpu.hostpool — multicore host-engine serving (ISSUE 5).

The designated degraded mode — PR 2's circuit-breaker host-drain and
PR 3's breaker-open queue drain — used to funnel every request through
the serial, single-process spec engine in :mod:`deppy_tpu.sat.host`, so
one wedged accelerator collapsed throughput to one core.  This package
is the first multi-process execution engine in the repo: a lazily
started, forkserver-backed pool of host-engine workers (sized by
``DEPPY_TPU_HOST_WORKERS`` / ``--host-workers``, default
``min(cpu_count, 8)``) that solves independent lanes of a batch
concurrently, with results bit-identical to the inline engine — models,
unsat cores, and step counts alike, because the workers and the inline
fallback run the single :func:`worker.solve_lane` implementation.

Every host-path consumer routes through
:func:`solve_host_problems`: the solver facade's ``backend="host"``
loop, the engine driver's ``_recovering`` host-fallback, and the
scheduler's breaker-open queue drain.  The full fault vocabulary rides
along: worker crashes retry on a fresh worker (charging
``deppy_fault_retries``), workers recycle after N solves, per-lane
deadlines cancel only the expired lane, a ``hostpool.dispatch`` fault
point scripts pool failures, a fork-restricted sandbox degrades to the
inline engine byte-identically, and graceful shutdown drains then
terminates the pool.

Metric families (``deppy_hostpool_*``, on the default registry and
mirrored into every service ``/metrics`` scrape) and the
``hostpool.dispatch`` / ``hostpool.worker_solve`` spans are tabled in
docs/observability.md; the fault rows live in docs/robustness.md.
"""

from .metrics import FAMILY_ORDER, render_metric_lines
from .pool import (
    HostPool,
    HostPoolError,
    configure_pool,
    default_pool,
    effective_workers,
    pool_workers,
    shutdown_default_pool,
    solve_host_problems,
    solve_inline,
)
from .worker import HostLaneResult, solve_lane

__all__ = [
    "FAMILY_ORDER",
    "HostLaneResult",
    "HostPool",
    "HostPoolError",
    "configure_pool",
    "default_pool",
    "effective_workers",
    "pool_workers",
    "render_metric_lines",
    "shutdown_default_pool",
    "solve_host_problems",
    "solve_inline",
    "solve_lane",
]
