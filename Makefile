# Build/test/deploy entry points — the analog of the reference Makefile
# (/root/reference/Makefile:72-126: unit, e2e, build, cli, deploy targets).
# The rebuild is pure Python + JAX, so "build" is a no-op beyond bytecode
# sanity; the deployable unit is the batch-resolution service image.

PYTHON ?= python
IMG ?= deppy-tpu:latest

.PHONY: all
all: verify unit

##@ Development

.PHONY: unit
unit: ## Default gate: every test at quick depth (trimmed randomized seeds, tests/_depth.py); ≤5 min on one core.
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q

.PHONY: unit-full
unit-full: ## Full-depth suite (all randomized seeds; ~18 min on one core — nightly / pre-release gate).
	$(PYTHON) -m pytest tests/ -q

.PHONY: unit-fast
unit-fast: ## Tests minus the slow randomized-equivalence suites.
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -k "not Randomized and not fleet"

.PHONY: verify
verify: ## Sanity: everything compiles and collects (reference `make verify` analog).
	$(PYTHON) -m compileall -q deppy_tpu tests scripts bench.py __graft_entry__.py
	$(PYTHON) -m pytest tests/ -q --collect-only >/dev/null

.PHONY: e2e
e2e: ## End-to-end: boot the service, exercise probes/metrics/resolve (reference Makefile:77-78 analog).
	bash scripts/e2e.sh

.PHONY: e2e-docker
e2e-docker: docker-build ## e2e against the built container image.
	DEPPY_E2E_MODE=docker IMG=$(IMG) bash scripts/e2e.sh

.PHONY: metrics-smoke
metrics-smoke: ## Boot the service on an ephemeral port, resolve the golden problem, assert a nonzero /metrics scrape.
	JAX_PLATFORMS=cpu $(PYTHON) scripts/metrics_smoke.py

.PHONY: test-telemetry
test-telemetry: ## Observability subsystem tests only (the `telemetry` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m telemetry

.PHONY: chaos-smoke
chaos-smoke: ## Inject device faults into the live service: assert retry recovery, breaker trip to host-only, and fault telemetry (ISSUE 2 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/chaos_smoke.py

.PHONY: test-chaos
test-chaos: ## Fault-domain subsystem tests only (the `chaos` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m chaos

.PHONY: sched-smoke
sched-smoke: ## Threaded clients against a CPU-backed server: assert request coalescing + cache hits (ISSUE 3 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/sched_smoke.py

.PHONY: test-sched
test-sched: ## Scheduler/cache subsystem tests only (the `sched` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m sched

.PHONY: trace-smoke
trace-smoke: ## Two concurrent traced requests against a live server: assert /debug/traces span trees + queue-wait histogram (ISSUE 4 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/trace_smoke.py

.PHONY: test-trace
test-trace: ## Distributed-tracing subsystem tests only (the `trace` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m trace

.PHONY: hostpool-smoke
hostpool-smoke: ## Multicore host-pool end-to-end: pool-vs-inline bit-identity, mid-batch worker crash, breaker-open sched drain (ISSUE 5 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/hostpool_smoke.py

.PHONY: test-hostpool
test-hostpool: ## Host worker-pool subsystem tests only (the `hostpool` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m hostpool

.PHONY: shard-smoke
shard-smoke: ## Mesh serving on a forced 8-device CPU platform: sharded-vs-unsharded byte-identity + poisoned-shard per-device fault domain (ISSUE 6 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/shard_smoke.py

.PHONY: test-shard
test-shard: ## Mesh-serving shard subsystem tests only (the `shard` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m shard

.PHONY: incremental-smoke
incremental-smoke: ## Churn replay against two live services: warm hits, chaos fallback, byte-identity vs the tier-off service (ISSUE 10 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/incremental_smoke.py

.PHONY: test-incremental
test-incremental: ## Incremental-resolution subsystem tests only (the `incremental` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m incremental

.PHONY: profile-smoke
profile-smoke: ## Profiled churn+mixed load end to end: armed trip-ledger events, the `deppy profile` cost model, two-tenant SLO burn rate on /metrics + /debug/slo, disarmed byte-identity (ISSUE 11 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/profile_smoke.py

.PHONY: test-profile
test-profile: ## Profiler + SLO subsystem tests only (the `profile` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m profile

.PHONY: bcp-smoke
bcp-smoke: ## Watched clause-bank engine end to end: impl byte-identity, device-vs-host bank fidelity, measured ladder pad-waste win, armed-guard zero-retrace (ISSUE 12 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/bcp_smoke.py

.PHONY: test-bcp
test-bcp: ## Watched clause-bank BCP subsystem tests only (the `bcp` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m bcp

.PHONY: portfolio-smoke
portfolio-smoke: ## Portfolio engine racing end to end: racing-on byte-identity, poisoned-entrant chaos, grad certification, profile race table, straggler triage (ISSUE 13 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/portfolio_smoke.py

.PHONY: test-portfolio
test-portfolio: ## Portfolio racing subsystem tests only (the `portfolio` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m portfolio

.PHONY: speculate-smoke
speculate-smoke: ## Speculative pre-resolution end to end: publish burst against a live service, warm-hit ratio + live-lane latency under load, preview read-only, speculate-off 404 + byte-identity (ISSUE 14 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/speculate_smoke.py

.PHONY: test-speculate
test-speculate: ## Speculative pre-resolution subsystem tests only (the `speculate` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m speculate

.PHONY: fleet-smoke
fleet-smoke: ## Replica fleet end to end: 3 local replicas + affinity router, mixed-tenant churn byte-identity + warm-hit ratio, publish fan-out, replica-kill retry, drain handoff, noisy-tenant fairness (ISSUE 15 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/fleet_smoke.py

.PHONY: test-fleet
test-fleet: ## Replica-fleet subsystem tests only (the `fleet` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m fleet

.PHONY: obs-smoke
obs-smoke: ## Fleet observability plane end to end: 3 replicas stream telemetry into one merged sink, /fleet/metrics rollups match per-replica scrapes, a routed request reassembles as one trace, an injected slowdown trips the drift watchdog on exactly the slow replica, deppy top + /debug/dump fan-out (ISSUE 16 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/obs_smoke.py

.PHONY: test-obs
test-obs: ## Fleet-observability subsystem tests only (the `obs` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m obs

.PHONY: routes-smoke
routes-smoke: ## Route-health plane end to end: a deliberately stale measured row trips the stale gauge, shadow probes run at the sampled rate under live load, a learned row is adopted (and cleared on shutdown), responses byte-identical to learn-off, `deppy routes` rebuilds the table from the sink alone (ISSUE 19 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/routes_smoke.py

.PHONY: test-routes
test-routes: ## Route-health subsystem tests only (the `routes` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m routes

.PHONY: sessions-smoke
sessions-smoke: ## Stateful resolution sessions end to end: interactive assume/test/resolve walk byte-identical to the one-shot oracle through a live 2-replica fleet, session survives a live drain, lease expiry on /metrics, sessions-off 404 byte-identity (ISSUE 20 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/sessions_smoke.py

.PHONY: test-sessions
test-sessions: ## Session-tier subsystem tests only (the `sessions` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m sessions

.PHONY: soak-smoke
soak-smoke: ## Elastic-fleet chaos survival gate, quick shape: open-loop load across replica kill / runtime join+arc-flip / drain / router failover, byte-identity vs a fault-free oracle (ISSUE 17 acceptance at --seconds 70; this target runs the 20s smoke).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/soak_smoke.py --seconds 20

.PHONY: soak-gate
soak-gate: ## The full ISSUE 17 acceptance run (>= 60s sustained load; writes benchmarks/results/soak_r17.json).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/soak_smoke.py

.PHONY: test-soak
test-soak: ## Soak/chaos survival tests only (the `soak` pytest marker; the full-length run needs -m "soak" without the slow deselect).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m soak

.PHONY: optimize-smoke
optimize-smoke: ## Optimization tier end to end: upgrade plan oracle-checked minimal-change against a live service, soft-constraint optimum with loop counters on /metrics, explain-why-not blocking set, opt-off 404 + resolve byte-identity (ISSUE 18 acceptance).
	JAX_PLATFORMS=cpu $(PYTHON) scripts/optimize_smoke.py

.PHONY: test-optimize
test-optimize: ## Optimization-tier subsystem tests only (the `optimize` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m optimize

.PHONY: lint
lint: ## Static analysis: the six deppy-lint checkers vs analysis/baseline.json (ISSUE 7/8 acceptance; docs/analysis.md).
	$(PYTHON) -m deppy_tpu lint

.PHONY: lint-fast
lint-fast: ## Pre-commit loop: checkers restricted to files changed vs HEAD (skips the repo-wide walk and absence-proving rules; run `make lint` before merging).
	$(PYTHON) -m deppy_tpu lint --changed

.PHONY: test-analysis
test-analysis: ## Static-analysis framework + lockdep tests only (the `analysis` pytest marker).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m analysis

.PHONY: test-lockdep
test-lockdep: ## The threaded-subsystem suites under runtime lock-order assertions (ISSUE 7 acceptance).
	JAX_PLATFORMS=cpu DEPPY_TPU_LOCKDEP=1 DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m "chaos or sched or hostpool"

.PHONY: lockdep-smoke
lockdep-smoke: ## Scripted lock-order inversion end to end: LockdepError + sink event + flight recorder + stats/trace CLIs.
	$(PYTHON) scripts/lockdep_smoke.py

.PHONY: test-compileguard
test-compileguard: ## Compile-contract suite (the `compileguard` pytest marker) plus the sched/shard tiers under the runtime guard (ISSUE 8 acceptance).
	DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m compileguard
	JAX_PLATFORMS=cpu DEPPY_TPU_COMPILE_GUARD=1 DEPPY_TEST_DEPTH=quick $(PYTHON) -m pytest tests/ -q -m "(sched or shard) and not slow"

.PHONY: compileguard-smoke
compileguard-smoke: ## Scripted jit-in-loop compile storm end to end: CompileGuardError + stamped sink events + `deppy compiles`/`deppy stats` + the static jit-no-memo finding.
	JAX_PLATFORMS=cpu $(PYTHON) scripts/compileguard_smoke.py

##@ Benchmarks

.PHONY: bench
bench: ## Headline benchmark (one JSON line; the driver's bench.py contract).
	$(PYTHON) bench.py

.PHONY: bench-suite
bench-suite: ## All five BASELINE.json workload configs.
	$(PYTHON) -m deppy_tpu.benchmarks.suite --out BENCH_SUITE.json

.PHONY: bench-suite-quick
bench-suite-quick: ## Suite at ~1/8 batch sizes (smoke).
	$(PYTHON) -m deppy_tpu.benchmarks.suite --quick

.PHONY: soak
soak: ## Differential fuzz: host vs tensor vs clause-sharded vs fused (scripts/soak.py).
	$(PYTHON) scripts/soak.py --cases 300

.PHONY: dist-dryrun
dist-dryrun: ## Two-process jax.distributed fleet solve vs a single-process oracle.
	$(PYTHON) scripts/dist_dryrun.py --processes 2 --devices-per-process 4

##@ Run

.PHONY: serve
serve: ## Run the batch-resolution service (API+metrics :8080, probes :8081).
	$(PYTHON) -m deppy_tpu serve

.PHONY: cli
cli: ## Show CLI help (reference `make cli` builds the cobra stub; ours is live).
	$(PYTHON) -m deppy_tpu --help

##@ Deployment

.PHONY: docker-build
docker-build: ## Build the service image.
	docker build -t $(IMG) .

.PHONY: deploy
deploy: ## Apply the kustomize tree (reference Makefile:106-126 analog).
	kubectl apply -k config/default

.PHONY: undeploy
undeploy:
	kubectl delete -k config/default
